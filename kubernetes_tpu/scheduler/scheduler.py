"""The scheduler: configurator + the scheduleOne loop.

Behavioral equivalent of the reference's ``pkg/scheduler/scheduler.go``
(Scheduler struct :61-88, Run :311-315, scheduleOne :427-600, assume :359,
bind :381, skipPodSchedule :620) and ``factory.go`` (Configurator :90-184,
MakeDefaultErrorFunc :316-362). One pod per cycle: Pop → Schedule → assume →
Reserve → Permit → async binding cycle; failures re-queue through the
error function with the moveRequestCycle protocol.

The TPU batch path (``kubernetes_tpu.sidecar``) plugs in behind the
``TPUBatchScheduler`` feature gate: when enabled the loop drains pod
*batches* and delegates assignment to the device solver, falling back to
this serial path whenever the sidecar declines a pod (clean fallback, like
an ``IsIgnorable`` extender — SURVEY.md section 5).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api.types import (
    NO_SCHEDULE,
    TAINT_NODE_UNREACHABLE,
    TAINT_NODE_UNSCHEDULABLE,
    Node,
    Pod,
    PodCondition,
    Taint,
    shallow_copy,
)
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config.feature_gates import FeatureGates
from kubernetes_tpu.config.types import KubeSchedulerConfiguration
from kubernetes_tpu.metrics import SchedulerMetrics
from kubernetes_tpu.observability import get_tracer
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.core import GenericScheduler, ScheduleResult
from kubernetes_tpu.scheduler.eventhandlers import EventHandlers, assigned
from kubernetes_tpu.scheduler.extender import HTTPExtender
from kubernetes_tpu.scheduler.framework import interface as fw
from kubernetes_tpu.scheduler.framework.cycle_state import CycleState
from kubernetes_tpu.scheduler.framework.plugins import new_in_tree_registry
from kubernetes_tpu.scheduler.framework.runtime import Framework, Registry
from kubernetes_tpu.scheduler.provider import PROVIDERS
from kubernetes_tpu.scheduler.queue import SchedulingQueue
from kubernetes_tpu.scheduler.types import PodInfo, QueuedPodInfo
from kubernetes_tpu.utils.clock import RealClock

PLUGIN_METRICS_SAMPLE_PERCENT = 10  # scheduler.go:56

_logger = logging.getLogger("kubernetes_tpu.scheduler")


def commit_target_stale(pod: Pod, node: Optional[Node]) -> Optional[str]:
    """Commit-time stale-node verdict for one (pod, flagged node) pair:
    the reason string when binding ``pod`` there would bind into the
    void, None when the pod may proceed (e.g. it tolerates the taint).
    ``node`` comes from ``SchedulerCache.commit_target_flags`` — None
    means the node vanished from the cache between snapshot and commit.
    Only called for flagged nodes, so the toleration scans here are off
    the no-churn hot path entirely."""
    if node is None:
        return "deleted"
    tolerations = pod.spec.tolerations
    if node.spec.unschedulable:
        cordon = Taint(TAINT_NODE_UNSCHEDULABLE, "", NO_SCHEDULE)
        if not any(t.tolerates(cordon) for t in tolerations):
            return "cordoned"
    for taint in node.spec.taints:
        if taint.key != TAINT_NODE_UNREACHABLE:
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return "unreachable"
    return None


class _Deps:
    """The Handle dependency bundle shared by all profile frameworks."""

    def __init__(self, scheduler: "Scheduler"):
        self._scheduler = scheduler
        self.parallelizer = None  # set by configurator

    def snapshot(self):
        return self._scheduler.algorithm.snapshot

    @property
    def client(self) -> ClusterStore:
        return self._scheduler.client

    @property
    def pod_nominator(self):
        return self._scheduler.queue

    @property
    def feature_gates(self) -> FeatureGates:
        return self._scheduler.feature_gates

    @property
    def extenders(self):
        return self._scheduler.algorithm.extenders

    @property
    def event_recorder(self):
        """The profile's EventRecorder (reference Handle.EventRecorder)."""
        return self._scheduler.recorder


class Scheduler:
    def __init__(
        self,
        client: ClusterStore,
        cache: SchedulerCache,
        queue: SchedulingQueue,
        profiles: Dict[str, Framework],
        algorithm: GenericScheduler,
        feature_gates: FeatureGates,
        metrics: SchedulerMetrics,
        clock=None,
        event_client=None,
    ):
        self.client = client
        self.cache = cache
        self.queue = queue
        self.profiles = profiles
        self.algorithm = algorithm
        self.feature_gates = feature_gates
        self.metrics = metrics
        self.clock = clock or RealClock()
        self._stop = threading.Event()
        self._bind_pool = ThreadPoolExecutor(max_workers=64,
                                             thread_name_prefix="binder")
        self._inflight_bindings = 0
        self._inflight_lock = threading.Lock()
        self._inflight_zero = threading.Condition(self._inflight_lock)
        self.batch_scheduler = None  # set by kubernetes_tpu.sidecar when gated on
        self._watch_handle = None
        # degraded mode: set while the client's circuit breaker is open
        # (apiserver unreachable). Binding pauses — the loop stops
        # popping — while watch ingestion keeps the cache warm; in-flight
        # binding cycles fail against the dead server and requeue through
        # the normal error function. Recovery clears the flag and wakes
        # every parked pod.
        self._degraded = threading.Event()
        self._degraded_since = 0.0
        self._degraded_lock = threading.Lock()
        self.event_handlers = EventHandlers(self)
        from kubernetes_tpu.client.events import EventRecorder

        # events flow through their OWN client when one is provided
        # (reference: the scheduler's EventBroadcaster writes through a
        # separate events client with its own rate limit — kube-
        # scheduler's eventClient in cmd/kube-scheduler/app/options).
        # Over REST this matters: 30k "Scheduled" events sharing the
        # bind client's token bucket would silently halve the bind
        # budget the reference never charges.
        self.recorder = EventRecorder(event_client or client,
                                      "default-scheduler")
        # bulk binds go async (the serial path's binding-goroutine
        # model, applied to whole batches) when the client is remote:
        # a REST round trip on the commit path would serialize every
        # batch cycle on wire latency. In-process stores bind inline —
        # same call, microseconds, and tests see bound pods
        # synchronously.
        self.async_bulk_bind = hasattr(client, "breaker")
        # cache mutations performed by the LAST commit_assignments_bulk
        # call (assumes + sync forgets): the sidecar's device-mirror
        # accounting needs the true count — gang members parked at
        # Permit are assumed but not committed, and counting only
        # commits made every gang batch invalidate the session (the
        # r5 state-only-rebuild-per-batch churn).
        self.last_bulk_commit_mutations = 0
        # -- multi-replica mode (scheduler/replicas.py installs these):
        # pod_shard(pod)->bool decides queue ownership (pod-hash
        # sharding: each pending pod belongs to exactly one replica);
        # node_shard(name)->bool restricts this replica's cache to a
        # disjoint node pool; commit_capacity_guard adds a commit-time
        # cache capacity probe (the optimistic-conflict guard for
        # replicas sharing ALL nodes — a sibling's binds land in this
        # cache via watch events, so a fit that evaporated since the
        # solve is refused and requeued instead of oversubscribing).
        self.pod_shard: Optional[Callable[[Pod], bool]] = None
        self.node_shard: Optional[Callable[[str], bool]] = None
        self.commit_capacity_guard = False
        self.replica_name = ""

    # ------------------------------------------------------------------
    @classmethod
    def create(
        cls,
        client: ClusterStore,
        config: Optional[KubeSchedulerConfiguration] = None,
        out_of_tree_registry: Optional[Registry] = None,
        provider: str = "DefaultProvider",
        feature_gates: Optional[FeatureGates] = None,
        metrics: Optional[SchedulerMetrics] = None,
        clock=None,
        event_client=None,
    ) -> "Scheduler":
        """The Configurator (factory.go:90-184 create/createFromProvider)."""
        config = config or KubeSchedulerConfiguration()
        errs = config.validate()
        if errs:
            raise ValueError("invalid scheduler configuration: " + "; ".join(errs))
        feature_gates = feature_gates or FeatureGates(config.feature_gates)
        metrics = metrics or SchedulerMetrics()
        cache = SchedulerCache()
        extenders = [HTTPExtender(e) for e in config.extenders]
        algorithm = GenericScheduler(
            cache,
            extenders=extenders,
            percentage_of_nodes_to_score=config.percentage_of_nodes_to_score,
            feature_gates=feature_gates,
        )

        registry = new_in_tree_registry()
        if out_of_tree_registry:
            registry.merge(out_of_tree_registry)
        default_plugins = PROVIDERS[provider](feature_gates)

        # fully initialize the scheduler BEFORE running plugin factories:
        # factories legitimately touch handle.client / pod_nominator
        # (reference NewFramework receives a working handle). The queue is
        # created first with the default less-func and rewired below —
        # it is empty until start(), so the swap is safe.
        queue = SchedulingQueue(
            clock=clock,
            pod_initial_backoff=config.pod_initial_backoff_seconds,
            pod_max_backoff=config.pod_max_backoff_seconds,
            metrics=metrics,
        )
        sched = cls(
            client, cache, queue, {}, algorithm,
            feature_gates, metrics, clock=clock, event_client=event_client,
        )
        deps = _Deps(sched)
        from kubernetes_tpu.utils.parallelize import Parallelizer

        deps.parallelizer = Parallelizer(config.parallelism)

        for profile in config.profiles:
            sched.profiles[profile.scheduler_name] = Framework(
                registry, profile, default_plugins, deps=deps, metrics=metrics
            )

        # all profiles must share the queue-sort function (profile.go:52)
        less_fns = {
            tuple(p.list_plugins()["queue_sort"])
            for p in sched.profiles.values()
        }
        if len(less_fns) != 1:
            raise ValueError("all profiles must use the same QueueSort plugin")
        any_profile = next(iter(sched.profiles.values()))
        queue._active_q._less = any_profile.queue_sort_less
        queue.sort_key = any_profile.queue_sort_key
        if queue.sort_key is not None:
            queue._active_q.set_sort_key(queue.sort_key)
        return sched

    # ------------------------------------------------------------------
    def start(self) -> None:
        """Wire event handlers and start background machinery (the
        informer-start + queue.Run portion of Run, scheduler.go:311)."""
        if self._watch_handle is None:
            self._watch_handle = self.client.watch(
                self.event_handlers.handle,
                batch_fn=self.event_handlers.handle_many,
            )
        # remote clients expose a circuit breaker; wire it to degraded
        # mode (the in-process store has no transport to lose)
        set_listener = getattr(self.client, "set_degraded_listener", None)
        if set_listener is not None:
            set_listener(self.set_degraded)
        # replay current state (the initial List of ListAndWatch)
        for node in self.client.list_nodes():
            if self.node_shard is not None and \
                    not self.node_shard(node.name):
                continue
            self.cache.add_node(node)
        for pod in self.client.list_pods():
            if assigned(pod):
                self.cache.add_pod(pod)
            elif self.event_handlers.responsible_for(pod):
                self.queue.add(pod)
        self.cache.run()
        self.queue.run()
        self.recorder.start()

    def run(self) -> threading.Thread:
        """Run the scheduling loop in a thread; returns it."""
        self.start()
        t = threading.Thread(target=self._loop, daemon=True, name="scheduleOne")
        t.start()
        return t

    def run_with_leader_election(self, identity: str = "scheduler-0",
                                 lease_name: str = "kube-scheduler",
                                 clock=None, lease_duration: float = 15.0,
                                 renew_deadline: float = 10.0,
                                 retry_period: float = 2.0):
        """HA wiring (cmd/kube-scheduler/app/server.go:199-208): informers
        start and caches sync BEFORE the election (a standby keeps warm
        state); only the lease holder runs the scheduling loop; losing
        the lease stops this scheduler for good — the reference
        ``klog.Fatalf``s there (server.go:205), because a deposed leader
        must never keep binding against a store another instance now
        owns. Returns the LeaderElector (``.is_leader`` for observers).
        """
        from kubernetes_tpu.client.leaderelection import (
            LeaderElectionConfig,
            LeaderElector,
        )

        self.start()
        self.lost_lease = False

        def on_started() -> None:
            threading.Thread(target=self._loop, daemon=True,
                             name=f"scheduleOne-{identity}").start()

        def on_stopped() -> None:
            # fatal-style: no re-acquire, no second loop
            self.elector.stop()
            if not self._stop.is_set():
                self.lost_lease = True
                self.stop()

        cfg = LeaderElectionConfig(
            lock_name=lease_name,
            identity=identity,
            lease_duration=lease_duration,
            renew_deadline=renew_deadline,
            retry_period=retry_period,
            on_started_leading=on_started,
            on_stopped_leading=on_stopped,
        )
        self.elector = LeaderElector(self.client, cfg, clock=clock)
        self.elector.run_in_thread()
        return self.elector

    def _loop(self) -> None:
        import logging

        logger = logging.getLogger("kubernetes_tpu.scheduler")
        while not self._stop.is_set():
            try:
                if self.batch_scheduler is not None:
                    self.batch_scheduler.run_batch(pop_timeout=0.2)
                else:
                    self.schedule_one(pop_timeout=0.2)
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("scheduling cycle failed; continuing")

    def stop(self) -> None:
        self._stop.set()
        # a stopped scheduler must release leadership: keeping the lease
        # renewing would block standby failover forever
        elector = getattr(self, "elector", None)
        if elector is not None:
            elector.stop()
        self.queue.close()
        self.cache.stop()
        if self._watch_handle is not None:
            self._watch_handle.stop()
            self._watch_handle = None
        if self.batch_scheduler is not None:
            # flush an in-flight profiler trace on short runs
            self.batch_scheduler.session.finish_profiling()
        self.recorder.stop()
        self._bind_pool.shutdown(wait=False)

    # -- degraded mode -------------------------------------------------
    def is_degraded(self) -> bool:
        return self._degraded.is_set()

    def set_degraded(self, degraded: bool) -> None:
        """Flip degraded mode (idempotent; the client's circuit-breaker
        listener). Entering pauses binding — new pops stop, in-flight
        binds fail-and-requeue on their own. Leaving accounts the
        outage into ``degraded_mode_seconds`` and moves every parked
        pod back to active so recovery is immediate, not
        backoff-delayed."""
        from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

        with self._degraded_lock:
            if degraded == self._degraded.is_set():
                return
            if degraded:
                self._degraded_since = time.monotonic()
                self._degraded.set()
                fabric_metrics().degraded_mode.set(1.0)
            else:
                self._degraded.clear()
                elapsed = time.monotonic() - self._degraded_since
                fabric_metrics().degraded_mode.set(0.0)
                fabric_metrics().degraded_mode_seconds.inc(amount=elapsed)
        if degraded:
            # outside the lock (dump is disk I/O — a recovery flip must
            # not wait on it): postmortem-before-the-mortem — degraded
            # entry means the apiserver is unreachable and a crash may
            # follow, so flush the flight recorder NOW (best-effort)
            tracer = get_tracer()
            tracer.event("sched.degraded_enter")
            if tracer.enabled and len(tracer):
                tracer.dump(reason="degraded", min_interval_s=5.0)
            return
        # outside the lock: queue wakeup can take the queue lock
        from kubernetes_tpu.scheduler import events as ev

        self.queue.move_all_to_active_or_backoff_queue(ev.CLIENT_RECOVERED)

    def wait_for_inflight_bindings(self, timeout: float = 30.0) -> bool:
        with self._inflight_zero:
            deadline = time.monotonic() + timeout
            while self._inflight_bindings > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_zero.wait(remaining)
            return True

    # ------------------------------------------------------------------
    def framework_for_pod(self, pod: Pod) -> Framework:
        fwk = self.profiles.get(pod.spec.scheduler_name)
        if fwk is None:
            raise KeyError(
                f"profile not found for scheduler name {pod.spec.scheduler_name!r}"
            )
        return fwk

    def skip_pod_schedule(self, fwk: Framework, pod: Pod) -> bool:
        """scheduler.go:620: deleting pods and already-assumed pods skip."""
        if pod.metadata.deletion_timestamp is not None:
            return True
        if self.cache.is_assumed_pod(pod):
            return True
        if assigned(pod):
            return True
        return False

    # ------------------------------------------------------------------
    def schedule_one(self, pop_timeout: Optional[float] = None) -> bool:
        """One scheduling cycle (scheduler.go:427). Returns False when the
        queue yielded nothing."""
        if self._degraded.is_set():
            # circuit open: binding is paused. Don't pop — a popped pod
            # would only fail its bind and burn a backoff round.
            time.sleep(min(pop_timeout or 0.05, 0.05))
            return False
        qpi = self.queue.pop(timeout=pop_timeout)
        if qpi is None:
            return False
        pod = qpi.pod
        try:
            fwk = self.framework_for_pod(pod)
        except KeyError:
            return True
        if self.skip_pod_schedule(fwk, pod):
            return True

        self.schedule_pod_serial(fwk, qpi)
        return True

    def schedule_pod_serial(self, fwk: Framework, qpi: QueuedPodInfo) -> None:
        """The serial algorithm + commit for one popped pod (the body of
        scheduleOne). Also the fallback path for pods the batch solver
        declines."""
        pod = qpi.pod
        pod_scheduling_cycle = self.queue.scheduling_cycle
        start = time.monotonic()
        state = CycleState()
        state.record_plugin_metrics = (
            random.randrange(100) < PLUGIN_METRICS_SAMPLE_PERCENT
        )

        try:
            result = self.algorithm.schedule(state, fwk, pod)
        except fw.FitError as fit_err:
            self._handle_fit_error(fwk, state, qpi, fit_err, pod_scheduling_cycle)
            self.metrics.schedule_attempts.inc("unschedulable", fwk.profile_name)
            return
        except Exception as err:  # noqa: BLE001 - mirrors the error func path
            self._record_failure(fwk, qpi, err, "SchedulerError", "",
                                 pod_scheduling_cycle)
            self.metrics.schedule_attempts.inc("error", fwk.profile_name)
            return

        self.metrics.scheduling_algorithm_duration.observe(time.monotonic() - start)
        self.commit_assignment(fwk, state, qpi, result, pod_scheduling_cycle,
                               start)

    def fail_unschedulable(self, fwk: Framework, qpi: QueuedPodInfo,
                           fit_err: "fw.FitError", cycle: int,
                           candidate_hints=None,
                           run_post_filter: bool = True) -> None:
        """Record an unschedulable outcome decided OUTSIDE the serial
        algorithm (the batch solver's declined pods): same PostFilter/
        preemption + requeue flow as the serial FitError branch, without
        re-running the full filter chain the device already evaluated.
        PreFilter still runs: preemption's dry-run re-executes Filter
        plugins against the CycleState, which must carry their
        PreFilter-computed data. ``candidate_hints`` (ranked node names
        from the batch preemption screen) bound the dry-run's candidate
        scan; the dry-run revalidates every hinted node.
        ``run_post_filter=False`` skips preemption when the caller has
        already proven it can't help (every node static-infeasible —
        nodesWherePreemptionMightHelp would be empty)."""
        state = CycleState()
        if run_post_filter and fwk.has_post_filter_plugins():
            # the serial path refreshes the snapshot inside Schedule; here
            # the device solve may have ridden the incremental mirror, so
            # the snapshot the preemption dry-run (and PreFilter) reads
            # could predate this epoch's commits — refresh (O(changed))
            self.algorithm.update_snapshot()
            fwk.run_pre_filter_plugins(state, qpi.pod)
            if candidate_hints is not None:
                from kubernetes_tpu.scheduler.framework.plugins import (
                    default_preemption as dp,
                )

                state.write(dp.DefaultPreemption.HINTS_KEY, candidate_hints)
            self._handle_fit_error(fwk, state, qpi, fit_err, cycle)
        else:
            self._record_failure(fwk, qpi, fit_err, "Unschedulable", "",
                                 cycle)
        self.metrics.schedule_attempts.inc("unschedulable", fwk.profile_name)

    def commit_assignment(
        self,
        fwk: Framework,
        state: CycleState,
        qpi: QueuedPodInfo,
        result: ScheduleResult,
        pod_scheduling_cycle: int,
        start: float,
        sync_bind: bool = False,
    ) -> bool:
        """assume → Reserve → Permit → (async) binding cycle — the commit
        half of scheduleOne, shared by the serial and batch paths.

        Returns True only when the pod was fully committed in this call
        (synchronous bind reached PostBind). Async commits return False;
        callers that must know (the batch session's device-state
        accounting) use sync_bind."""
        pod = qpi.pod
        # stale-node guard (chaos_nodes): the algorithm ran against a
        # snapshot that may predate a node death/cordon/unreachable
        # taint — binding there would bind into the void (the store
        # accepts binds to nonexistent nodes). One cache probe per
        # commit; requeue through the normal error function.
        flagged = self.cache.commit_target_flags((result.suggested_host,))
        if flagged:
            reason = commit_target_stale(pod, flagged[result.suggested_host])
            if reason is not None:
                self._reject_stale_commit(
                    fwk, qpi, result.suggested_host, reason, "serial",
                    pod_scheduling_cycle)
                return False
        if self.commit_capacity_guard and self.cache.commit_fits(
                ((pod, result.suggested_host),))[0] is not None:
            # multi-replica optimistic conflict: a sibling's binds
            # (applied to this cache via watch events) consumed the
            # capacity this solve counted on — refuse and requeue, the
            # next attempt solves against the post-conflict world
            self._reject_stale_commit(
                fwk, qpi, result.suggested_host,
                "out of capacity (concurrent replica commits)",
                "capacity", pod_scheduling_cycle)
            return False
        # assume: tell the cache the pod is (going to be) bound (scheduler.go:359)
        assumed_pod = shallow_copy(pod)
        assumed_pod.spec = shallow_copy(pod.spec)
        assumed_pod.spec.node_name = result.suggested_host
        # reuse the queue's parse — the copy differs only in nodeName
        PodInfo.derived(assumed_pod, qpi.pod_info)
        try:
            self.cache.assume_pod(assumed_pod)
        except ValueError as err:
            self._record_failure(fwk, qpi, err, "SchedulerError", "",
                                 pod_scheduling_cycle)
            return False
        self.queue.delete_nominated_pod_if_exists(pod)

        # Reserve
        status = fwk.run_reserve_plugins_reserve(state, assumed_pod,
                                                result.suggested_host)
        if not fw.Status.is_ok(status):
            self._forget_and_fail(fwk, state, qpi, assumed_pod, result,
                                  status.as_error(), pod_scheduling_cycle)
            return False

        # Permit
        status = fwk.run_permit_plugins(state, assumed_pod, result.suggested_host)
        if status is not None and status.code not in (fw.SUCCESS, fw.WAIT):
            self._unreserve_forget_fail(fwk, state, qpi, assumed_pod, result,
                                        status.as_error(), pod_scheduling_cycle)
            return False

        with self._inflight_lock:
            self._inflight_bindings += 1
        self.metrics.goroutines.inc("binding")
        if sync_bind and status is None:
            # batch path: bindings are in-process; skipping the thread
            # hop roughly halves per-pod commit cost
            return self._binding_cycle(fwk, state, qpi, assumed_pod, result,
                                       pod_scheduling_cycle, start)
        else:
            # binding cycle runs async (scheduler.go:540): the loop continues
            try:
                self._bind_pool.submit(
                    self._binding_cycle, fwk, state, qpi, assumed_pod,
                    result, pod_scheduling_cycle, start,
                )
            except RuntimeError:
                # pool already shut down (stop() raced a late commit):
                # release the in-flight slot; the pod's state dies with
                # this scheduler instance
                self.metrics.goroutines.dec("binding")
                with self._inflight_zero:
                    self._inflight_bindings -= 1
                    if self._inflight_bindings == 0:
                        self._inflight_zero.notify_all()
        return False

    def commit_assignments_bulk(
        self, fwk: Framework, commits: List[tuple]
    ) -> tuple:
        """Commit a whole solved batch: the semantics of N
        ``commit_assignment(..., sync_bind=True)`` calls with the
        per-pod O(lock + dispatch) overheads amortized — bulk assume
        (one cache lock), bulk bind (one store lock + one batched watch
        delivery), bulk finish-binding. Every per-pod framework hook
        (Reserve, Permit, WaitOnPermit, PreBind, PostBind) still runs
        per pod in order; pods whose Permit returns WAIT drop to the
        async binding cycle exactly as in the serial path.

        ``commits``: list of (qpi, result, cycle, start). Returns
        (committed, failed) where failed counts pods that were rejected
        host-side after the device counted them (the caller's
        device-mirror accounting needs to know).

        Side channel: ``self.last_bulk_commit_mutations`` is set to the
        number of cache mutations THIS call performed synchronously
        (one assume per pod that passed the stale guard, plus one
        forget per sync rejection) — the sidecar validates its device
        mirror against this count, so pods parked at Permit (gangs)
        count via their assume even though they bind asynchronously.

        When ``self.async_bulk_bind`` is set (remote clients), the
        final bulk Bind ships on the binding pool instead of blocking
        this call — the batch loop must not serialize every cycle on a
        wire round trip. Failures there unreserve/forget/requeue
        exactly as the sync path would, just later; the extra forget
        invalidates the device mirror through the normal arithmetic."""
        # --- stale-node guard (chaos_nodes): ONE cache probe for the
        # whole batch; assignments targeting nodes that died, were
        # cordoned, or went unreachable since the solve are refused
        # before assume and requeued — never bound into the void.
        flagged = self.cache.commit_target_flags(
            {r.suggested_host for _, r, _, _ in commits}
        ) if commits else {}
        stale_failed = 0
        if flagged:
            live_commits: List[tuple] = []
            for item in commits:
                qpi, result, cycle, _start = item
                node = flagged.get(result.suggested_host, False)
                reason = commit_target_stale(qpi.pod, node) \
                    if node is not False else None
                if reason is None:
                    live_commits.append(item)
                else:
                    self._reject_stale_commit(
                        fwk, qpi, result.suggested_host, reason, "bulk",
                        cycle)
                    stale_failed += 1
            commits = live_commits
        if self.commit_capacity_guard and commits:
            # multi-replica optimistic conflict guard: ONE cache probe
            # for the whole batch, cumulative per node — targets whose
            # remaining capacity a sibling replica consumed since the
            # solve are refused before assume and requeued
            verdicts = self.cache.commit_fits(
                [(qpi.pod, r.suggested_host)
                 for qpi, r, _, _ in commits])
            if any(v is not None for v in verdicts):
                live_commits = []
                for item, verdict in zip(commits, verdicts):
                    if verdict is None:
                        live_commits.append(item)
                    else:
                        qpi, result, cycle, _start = item
                        self._reject_stale_commit(
                            fwk, qpi, result.suggested_host,
                            "out of capacity (concurrent replica "
                            "commits)", "capacity", cycle)
                        stale_failed += 1
                commits = live_commits
        # --- assume (bulk): share the queue's parse via PodInfo.derived
        prepared: List[tuple] = []
        assumed_pods: List[Pod] = []
        for qpi, result, cycle, start in commits:
            pod = qpi.pod
            assumed = shallow_copy(pod)
            assumed.spec = shallow_copy(pod.spec)
            assumed.spec.node_name = result.suggested_host
            PodInfo.derived(assumed, qpi.pod_info)
            prepared.append((qpi, result, cycle, start, assumed))
            assumed_pods.append(assumed)
        errors = self.cache.assume_pods(assumed_pods)
        live: List[tuple] = []
        for item, err in zip(prepared, errors):
            if err is None:
                live.append(item)
                self.queue.delete_nominated_pod_if_exists(item[0].pod)
            else:
                self._record_failure(fwk, item[0], ValueError(err),
                                     "SchedulerError", "", item[2])
        failed = stale_failed + len(prepared) - len(live)
        # cache-mutation ledger: one assume per live pod so far; every
        # sync rejection below adds its forget
        mutations = len(live)

        # --- Reserve + Permit (per-pod hook contract)
        has_reserve = bool(fwk.reserve_plugins)
        has_permit = bool(fwk.permit_plugins)
        has_pre_bind = bool(fwk.pre_bind_plugins)
        has_post_bind = bool(fwk.post_bind_plugins)
        sync_items: List[tuple] = []   # (qpi, result, cycle, start, assumed, state)
        for qpi, result, cycle, start, assumed in live:
            state = CycleState()
            if has_reserve:
                status = fwk.run_reserve_plugins_reserve(
                    state, assumed, result.suggested_host)
                if not fw.Status.is_ok(status):
                    self._forget_and_fail(fwk, state, qpi, assumed, result,
                                          status.as_error(), cycle)
                    failed += 1
                    mutations += 1
                    continue
            if has_permit:
                status = fwk.run_permit_plugins(state, assumed,
                                                result.suggested_host)
                if status is not None and status.code not in (fw.SUCCESS,
                                                              fw.WAIT):
                    self._unreserve_forget_fail(fwk, state, qpi, assumed,
                                                result, status.as_error(),
                                                cycle)
                    failed += 1
                    mutations += 1
                    continue
                if status is not None and status.code == fw.WAIT:
                    # gang/permit-parked pods bind asynchronously
                    with self._inflight_lock:
                        self._inflight_bindings += 1
                    self.metrics.goroutines.inc("binding")
                    self._bind_pool.submit(
                        self._binding_cycle, fwk, state, qpi, assumed,
                        result, cycle, start,
                    )
                    continue
            sync_items.append((qpi, result, cycle, start, assumed, state))

        # --- PreBind (per pod), then bulk Bind
        bindable: List[tuple] = []
        for qpi, result, cycle, start, assumed, state in sync_items:
            if has_permit:
                # permit returned SUCCESS; WaitOnPermit is then a cheap
                # no-waiting-pod lookup, kept for hook-order parity
                status = fwk.wait_on_permit(assumed)
                if not fw.Status.is_ok(status):
                    self._unreserve_forget_fail(fwk, state, qpi, assumed,
                                                result, status.as_error(),
                                                cycle)
                    failed += 1
                    mutations += 1
                    continue
            if has_pre_bind:
                status = fwk.run_pre_bind_plugins(state, assumed,
                                                  result.suggested_host)
                if not fw.Status.is_ok(status):
                    self._unreserve_forget_fail(fwk, state, qpi, assumed,
                                                result, status.as_error(),
                                                cycle)
                    failed += 1
                    mutations += 1
                    continue
            bindable.append((qpi, result, cycle, start, assumed, state))

        # extender binders (rare) take the per-pod path; the rest bind
        # in one bulk call
        ext_binders = [e for e in self.algorithm.extenders if e.is_binder()]
        bulk: List[tuple] = []
        committed = 0
        for item in bindable:
            qpi, result, cycle, start, assumed, state = item
            if ext_binders and any(e.is_interested(assumed)
                                   for e in ext_binders):
                err = self._bind(fwk, state, assumed, result.suggested_host)
                if err is not None:
                    self._unreserve_forget_fail(fwk, state, qpi, assumed,
                                                result, err, cycle)
                    failed += 1
                    mutations += 1
                else:
                    self._observe_scheduled(fwk, qpi, start,
                                            result.suggested_host)
                    committed += 1
            else:
                bulk.append(item)
        if bulk:
            if self.async_bulk_bind:
                # ship the whole batch's Bind on the binding pool: the
                # commit loop keeps solving while the bulk request is
                # on the wire (the serial path's per-pod binding
                # goroutine, amortized to one per batch). Pods already
                # count as assumed in the mutation ledger; a wire-level
                # failure forgets them asynchronously, which the
                # device-mirror arithmetic reads as an invalidation.
                with self._inflight_lock:
                    self._inflight_bindings += 1
                self.metrics.goroutines.inc("binding")
                try:
                    self._bind_pool.submit(self._complete_bulk_bind,
                                           fwk, bulk, has_post_bind)
                except RuntimeError:
                    # pool already shut down (stop() raced a late
                    # commit): same accounting as the serial submit race
                    self.metrics.goroutines.dec("binding")
                    with self._inflight_zero:
                        self._inflight_bindings -= 1
                        if self._inflight_bindings == 0:
                            self._inflight_zero.notify_all()
            else:
                n = self._bulk_bind_now(fwk, bulk, has_post_bind)
                committed += n
                failed += len(bulk) - n
                mutations += len(bulk) - n   # one forget per rejection
        self.last_bulk_commit_mutations = mutations
        return committed, failed

    def _bulk_bind_now(self, fwk: Framework, bulk: List[tuple],
                       has_post_bind: bool) -> int:
        """The bulk Bind + PostBind + finish-binding tail shared by the
        sync and async paths. Returns the number bound; failures
        unreserve/forget/requeue per pod (each forget bumps the cache
        mutation counter, which the async path relies on to invalidate
        the device mirror)."""
        t_bind = time.monotonic()
        statuses = fwk.run_bind_plugins_bulk(
            [i[5] for i in bulk], [i[4] for i in bulk],
            [i[1].suggested_host for i in bulk],
        )
        get_tracer().record("bind.bulk", t_bind, pods=len(bulk))
        bound: List[Pod] = []
        observed: List[tuple] = []
        committed = 0
        for item, status in zip(bulk, statuses):
            qpi, result, cycle, start, assumed, state = item
            if not fw.Status.is_ok(status):
                self._unreserve_forget_fail(fwk, state, qpi, assumed,
                                            result, status.as_error(),
                                            cycle)
                continue
            bound.append(assumed)
            if has_post_bind:
                fwk.run_post_bind_plugins(state, assumed,
                                          result.suggested_host)
            observed.append((qpi, start, result.suggested_host))
            committed += 1
        self._observe_scheduled_bulk(fwk, observed)
        self.cache.finish_binding_many(bound)
        return committed

    def _complete_bulk_bind(self, fwk: Framework, bulk: List[tuple],
                            has_post_bind: bool) -> None:
        try:
            try:
                self._bulk_bind_now(fwk, bulk, has_post_bind)
            except Exception as err:  # noqa: BLE001 — transport died
                # (retries exhausted, server gone): every pod in the
                # batch unwinds exactly as a failed sync bind would —
                # unreserve, forget, SchedulerError requeue; the next
                # attempt sees the post-outage world
                for qpi, result, cycle, _start, assumed, state in bulk:
                    self._unreserve_forget_fail(fwk, state, qpi, assumed,
                                                result, err, cycle)
        finally:
            self.metrics.goroutines.dec("binding")
            with self._inflight_zero:
                self._inflight_bindings -= 1
                if self._inflight_bindings == 0:
                    self._inflight_zero.notify_all()

    def _observe_scheduled(self, fwk: Framework, qpi: QueuedPodInfo,
                           start: float, node_name: str = "") -> None:
        now = time.monotonic()
        self.metrics.e2e_scheduling_duration.observe(now - start, "scheduled")
        self.metrics.schedule_attempts.inc("scheduled", fwk.profile_name)
        self.metrics.pod_scheduling_attempts.observe(qpi.attempts)
        self.metrics.pod_scheduling_duration.observe(
            now - qpi.initial_attempt_timestamp, str(qpi.attempts))
        pod = qpi.pod
        tracer = get_tracer()
        if tracer.enabled and pod.uid and tracer.sampled(pod.uid):
            # the bind-completing hop of the pod's causal trace:
            # pop → algorithm/solve → commit → bound
            tracer.record("sched.bind", start, now, trace=pod.uid,
                          node=node_name, attempts=qpi.attempts)
        self.recorder.eventf(
            pod, "Normal", "Scheduled",
            "Successfully assigned %s/%s to %s",
            pod.namespace, pod.name, node_name,
        )

    def _observe_scheduled_bulk(self, fwk: Framework, observed) -> None:
        """Batched ``_observe_scheduled`` for the bulk commit path:
        ``observed`` is a list of (qpi, start, node_name). Metric locks
        are taken once per batch instead of 4x per pod, and the
        Scheduled event's formatting defers to the recorder's flush
        thread."""
        if not observed:
            return
        now = time.monotonic()
        m = self.metrics
        m.e2e_scheduling_duration.observe_many(
            [now - start for _, start, _ in observed], "scheduled")
        m.schedule_attempts.inc("scheduled", fwk.profile_name,
                                amount=len(observed))
        m.pod_scheduling_attempts.observe_many(
            [qpi.attempts for qpi, _, _ in observed])
        by_attempts: dict = {}
        for qpi, _, _ in observed:
            by_attempts.setdefault(qpi.attempts, []).append(
                now - qpi.initial_attempt_timestamp)
        for attempts, values in by_attempts.items():
            m.pod_scheduling_duration.observe_many(values, str(attempts))
        tracer = get_tracer()
        if tracer.enabled:
            for qpi, start, node_name in observed:
                uid = qpi.pod.uid
                if uid and tracer.sampled(uid):
                    tracer.record("sched.bind", start, now, trace=uid,
                                  node=node_name, attempts=qpi.attempts)
        recorder = self.recorder
        for qpi, _, node_name in observed:
            pod = qpi.pod
            recorder.eventf(
                pod, "Normal", "Scheduled",
                "Successfully assigned %s/%s to %s",
                pod.namespace, pod.name, node_name,
            )

    # ------------------------------------------------------------------
    def _binding_cycle(
        self,
        fwk: Framework,
        state: CycleState,
        qpi: QueuedPodInfo,
        assumed_pod: Pod,
        result: ScheduleResult,
        cycle: int,
        start: float,
    ) -> bool:
        try:
            status = fwk.wait_on_permit(assumed_pod)
            if not fw.Status.is_ok(status):
                self._unreserve_forget_fail(fwk, state, qpi, assumed_pod, result,
                                            status.as_error(), cycle)
                return False
            status = fwk.run_pre_bind_plugins(state, assumed_pod,
                                              result.suggested_host)
            if not fw.Status.is_ok(status):
                self._unreserve_forget_fail(fwk, state, qpi, assumed_pod, result,
                                            status.as_error(), cycle)
                return False
            err = self._bind(fwk, state, assumed_pod, result.suggested_host)
            if err is not None:
                self._unreserve_forget_fail(fwk, state, qpi, assumed_pod, result,
                                            err, cycle)
                return False
            fwk.run_post_bind_plugins(state, assumed_pod, result.suggested_host)
            self._observe_scheduled(fwk, qpi, start, result.suggested_host)
            return True
        finally:
            self.metrics.goroutines.dec("binding")
            with self._inflight_zero:
                self._inflight_bindings -= 1
                if self._inflight_bindings == 0:
                    self._inflight_zero.notify_all()

    def _bind(self, fwk: Framework, state: CycleState, pod: Pod,
              node_name: str) -> Optional[Exception]:
        """scheduler.go:381: extender binders take precedence, then the
        framework's bind plugins; FinishBinding starts the assumed TTL."""
        try:
            bound = False
            for ext in self.algorithm.extenders:
                if ext.is_binder() and ext.is_interested(pod):
                    ext.bind(pod, node_name)
                    bound = True
                    break
            if not bound:
                status = fwk.run_bind_plugins(state, pod, node_name)
                if not fw.Status.is_ok(status):
                    return status.as_error()
            self.cache.finish_binding(pod)
            return None
        except Exception as e:  # noqa: BLE001
            return e

    # ------------------------------------------------------------------
    def _handle_fit_error(self, fwk: Framework, state: CycleState,
                          qpi: QueuedPodInfo, fit_err: fw.FitError,
                          cycle: int) -> None:
        """PostFilter (preemption) then record + requeue (scheduler.go:465)."""
        nominated_node = ""
        if fwk.has_post_filter_plugins():
            self.metrics.preemption_attempts.inc()
            # preemption drives client writes (victim deletes, status);
            # a transport failure mid-dry-run must still fall through to
            # record + REQUEUE, not lose the pod
            try:
                result, status = fwk.run_post_filter_plugins(
                    state, qpi.pod, fit_err.filtered_nodes_statuses
                )
            except Exception as post_err:  # noqa: BLE001
                result, status = None, fw.Status(
                    fw.ERROR, f"PostFilter failed: {post_err}")
            if fw.Status.is_ok(status) and result is not None:
                nominated_node = result.nominated_node_name
        self._record_failure(fwk, qpi, fit_err, "Unschedulable",
                             nominated_node, cycle)

    def _forget_and_fail(self, fwk, state, qpi, assumed_pod, result, err,
                         cycle) -> None:
        try:
            self.cache.forget_pod(assumed_pod)
        except ValueError:
            pass
        self._record_failure(fwk, qpi, err, "SchedulerError", "", cycle)

    @staticmethod
    def _note_bind_conflict(err: Exception) -> None:
        """Count a bind the STORE refused because another writer got
        there first — the same-pod CAS losing half of multi-replica
        optimistic concurrency ("already assigned": a sibling replica
        bound this pod; "uid mismatch": it was deleted and recreated in
        flight; "capacity conflict": the partitioned store's bind-time
        ledger arbitrated a node race). The loser unwinds through the
        normal unreserve/forget/requeue path; this just makes the
        conflict visible on the stale-bind series the chaos invariants
        watch."""
        msg = str(err)
        if "capacity conflict" in msg:
            path = "bind_conflict"
        elif "already assigned" in msg or "uid mismatch" in msg:
            path = "replica_conflict"
        else:
            return
        from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

        fabric_metrics().stale_binds_rejected_total.inc(path)

    def _unreserve_forget_fail(self, fwk, state, qpi, assumed_pod, result,
                               err, cycle) -> None:
        self._note_bind_conflict(err)
        fwk.run_reserve_plugins_unreserve(state, assumed_pod,
                                          result.suggested_host)
        gang = fwk.get_plugin("Coscheduling")
        if gang is not None:
            gang.unreserve_group(assumed_pod)
        self._forget_and_fail(fwk, state, qpi, assumed_pod, result, err, cycle)

    def _reject_stale_commit(self, fwk: Framework, qpi: QueuedPodInfo,
                             node_name: str, reason: str, path: str,
                             cycle: int) -> None:
        """Refuse to commit an assignment whose target node went stale
        between snapshot and commit: count it, then route the pod back
        through the normal error function (SchedulerError → backoff
        requeue; the next attempt solves against the post-churn
        state)."""
        from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

        fabric_metrics().stale_binds_rejected_total.inc(path)
        _logger.debug("refusing stale bind of %s/%s to %s node %s (%s)",
                      qpi.pod.namespace, qpi.pod.name, reason, node_name,
                      path)
        err = RuntimeError(
            f"commit target node {node_name!r} is {reason} "
            f"(assignment solved against a stale snapshot)")
        self._record_failure(fwk, qpi, err, "SchedulerError", "", cycle)

    def _record_failure(self, fwk: Framework, qpi: QueuedPodInfo,
                        err: Exception, reason: str, nominated_node: str,
                        cycle: int) -> None:
        """recordSchedulingFailure (scheduler.go:319) +
        MakeDefaultErrorFunc (factory.go:316-362)."""
        pod = qpi.pod
        # the operator-facing record (scheduler.go:331 recordSchedulingFailure
        # → FailedScheduling event)
        self.recorder.event(pod, "Warning", "FailedScheduling", str(err))
        # status writes are BEST-EFFORT: over REST they can fail (server
        # down, overload pushback, retry budget spent) and an exception
        # here must never skip the requeue below — a pod dropped between
        # queues is parked forever, the exact lost-pod failure the chaos
        # ring exists to catch
        try:
            self.client.patch_pod_condition(
                pod.namespace, pod.name,
                PodCondition("PodScheduled", "False", reason, str(err)),
            )
            if nominated_node:
                self.client.set_nominated_node_name(pod.namespace,
                                                    pod.name,
                                                    nominated_node)
        except Exception:  # noqa: BLE001 — usually transport loss; a
            # real defect must still be visible in the logs
            _logger.debug("status write failed for %s/%s (requeueing "
                          "regardless)", pod.namespace, pod.name,
                          exc_info=True)
        if nominated_node:
            pod.status.nominated_node_name = nominated_node
            self.queue.add_nominated_pod(pod, nominated_node)
        # requeue only pods that still exist unassigned (factory.go:340);
        # when the existence check itself fails, assume the pod lives and
        # requeue — a later cycle re-checks against recovered state
        try:
            current = self.client.get_pod(pod.namespace, pod.name)
        except Exception:  # noqa: BLE001 — transport failure
            _logger.debug("existence check failed for %s/%s (assuming "
                          "it lives)", pod.namespace, pod.name,
                          exc_info=True)
            current = pod
        if current is not None and not assigned(current):
            try:
                # scheduler-internal failures retry on the backoff curve;
                # only genuine fit failures park for an unblocking event
                self.queue.add_unschedulable_if_not_present(
                    qpi, cycle,
                    prefer_backoff=(reason == "SchedulerError"))
            except ValueError:
                pass
