"""Immutable per-cycle cluster view (reference
``internal/cache/snapshot.go:28-41``): node-info map, zone-interleaved node
list, and affinity-specialized sublists, implementing the SharedLister
surface plugins read (``framework/listers.go``)."""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.scheduler.types import ImageStateSummary, NodeInfo


class Snapshot:
    def __init__(self):
        self.node_info_map: Dict[str, NodeInfo] = {}
        self.node_info_list: List[NodeInfo] = []
        self.have_pods_with_affinity_node_info_list: List[NodeInfo] = []
        self.have_pods_with_required_anti_affinity_node_info_list: List[NodeInfo] = []
        self.generation: int = 0

    # --- SharedLister / NodeInfoLister surface ------------------------
    def list(self) -> List[NodeInfo]:
        return self.node_info_list

    def have_pods_with_affinity_list(self) -> List[NodeInfo]:
        return self.have_pods_with_affinity_node_info_list

    def have_pods_with_required_anti_affinity_list(self) -> List[NodeInfo]:
        return self.have_pods_with_required_anti_affinity_node_info_list

    def get(self, node_name: str) -> Optional[NodeInfo]:
        return self.node_info_map.get(node_name)

    def num_nodes(self) -> int:
        return len(self.node_info_list)

    # --- pods view (reference snapshot podLister) ---------------------
    def pods(self) -> List[Pod]:
        return [pi.pod for ni in self.node_info_list for pi in ni.pods]


def new_snapshot(pods: Iterable[Pod], nodes: Iterable[Node]) -> Snapshot:
    """Test/algorithm constructor (reference snapshot.go:51 NewSnapshot):
    builds a coherent snapshot directly from object lists, including
    cluster-wide image states."""
    s = Snapshot()
    by_name: Dict[str, NodeInfo] = {}
    for node in nodes:
        ni = NodeInfo()
        ni.set_node(node)
        by_name[node.name] = ni
    for pod in pods:
        if pod.spec.node_name and pod.spec.node_name in by_name:
            by_name[pod.spec.node_name].add_pod(pod)

    # image states: size + how many nodes hold each image
    image_nodes: Dict[str, set] = {}
    image_size: Dict[str, int] = {}
    for node in nodes:
        for img in node.status.images:
            for name in img.names:
                image_nodes.setdefault(name, set()).add(node.name)
                image_size[name] = img.size_bytes
    for node in nodes:
        ni = by_name[node.name]
        for img in node.status.images:
            for name in img.names:
                ni.image_states[name] = ImageStateSummary(
                    size=image_size[name], num_nodes=len(image_nodes[name])
                )

    s.node_info_map = by_name
    s.node_info_list = list(by_name.values())
    s.have_pods_with_affinity_node_info_list = [
        ni for ni in s.node_info_list if ni.pods_with_affinity
    ]
    s.have_pods_with_required_anti_affinity_node_info_list = [
        ni for ni in s.node_info_list if ni.pods_with_required_anti_affinity
    ]
    return s
