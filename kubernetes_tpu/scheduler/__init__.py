"""Scheduler stack: cache, snapshot, queue, framework, plugins, core loop.

Re-implements the capability surface of the reference's ``pkg/scheduler``
(see SURVEY.md sections 2.3/2.4 and 3.1-3.3) with a TPU batch path layered
on top (``kubernetes_tpu.ops`` / ``kubernetes_tpu.parallel``).
"""
