"""Assume-aware cluster cache with incremental snapshotting.

Behavioral equivalent of the reference's ``pkg/scheduler/internal/cache/cache.go``:
optimistically-bound ("assumed") pods with a TTL (30s default, cache.go:42),
a doubly-linked list of NodeInfos ordered by most-recently-updated Generation
so ``update_snapshot`` touches only the changed prefix (cache.go:203-287),
and cluster-wide image-state aggregation.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set

from kubernetes_tpu.api.types import TAINT_NODE_UNREACHABLE, Node, Pod
from kubernetes_tpu.scheduler.node_tree import NodeTree
from kubernetes_tpu.scheduler.snapshot import Snapshot
from kubernetes_tpu.scheduler.types import (
    ImageStateSummary,
    NodeInfo,
    get_pod_key,
    next_generation,
)

DEFAULT_ASSUME_TTL = 30.0
CLEANUP_INTERVAL = 1.0


def _pod_mirror_changed(old: Pod, new: Pod) -> bool:
    """Whether a pod update changes anything the device mirror tracks
    (requests, affinity, node assignment, labels, deletion). Status-only
    patches — the overwhelming majority of live-informer MODIFIED events —
    must not invalidate the mirror."""
    return (
        old.spec != new.spec
        or old.metadata.labels != new.metadata.labels
        or old.metadata.deletion_timestamp != new.metadata.deletion_timestamp
    )


def _node_mirror_changed(old: Node, new: Node) -> bool:
    """Whether a node update changes anything the device mirror tracks
    (allocatable/images via status, taints/unschedulable via spec,
    topology via labels). Heartbeat-only updates must not invalidate."""
    if old is None:
        return True
    # status.conditions carries heartbeat timestamps — deliberately excluded
    return (
        old.status.allocatable != new.status.allocatable
        or old.status.images != new.status.images
        or old.spec != new.spec
        or old.metadata.labels != new.metadata.labels
    )


class _PodState:
    __slots__ = ("pod", "deadline", "binding_finished")

    def __init__(self, pod: Pod):
        self.pod = pod
        self.deadline: Optional[float] = None
        self.binding_finished = False


class _NodeInfoListItem:
    __slots__ = ("info", "next", "prev")

    def __init__(self, info: NodeInfo):
        self.info = info
        self.next: Optional["_NodeInfoListItem"] = None
        self.prev: Optional["_NodeInfoListItem"] = None


class _ImageState:
    """Cluster-wide per-image state. Exposed directly (shared, live) as the
    NodeInfo image-state summary so num_nodes never goes stale as other
    nodes gain/lose the image."""

    __slots__ = ("size", "nodes")

    def __init__(self, size: int):
        self.size = size
        self.nodes: Set[str] = set()

    @property
    def num_nodes(self) -> int:
        return len(self.nodes)


class SchedulerCache:
    def __init__(self, ttl: float = DEFAULT_ASSUME_TTL, now=time.monotonic):
        self._ttl = ttl
        self._now = now
        self._lock = threading.RLock()
        # Monotonic counter of every NodeInfo-affecting mutation. The TPU
        # solver session snapshots it after committing a batch; a mismatch
        # at the next batch means the cluster changed underneath the
        # device-resident state mirror, which must then be rebuilt.
        # Informer confirmations of assumed pods (add_pod with a matching
        # nodeName) change nothing the device mirror tracks, so they do
        # not bump it.
        self._mutation_seq = 0
        # Counter of node-SET changes only (a node appearing or
        # vanishing, not updates). The solver session anchors its
        # encoded node planes to this: mutation_seq arithmetic can be
        # laundered by compensating bumps, but an encoding built over a
        # node set from another epoch must never serve the incremental
        # path (chaos_nodes: mass deletion must force a re-encode, not
        # a spin of declines against ghost columns).
        self._node_set_seq = 0
        # commit timestamp (Event.ts) of the NEWEST watch event the
        # event handlers applied to this cache — the snapshot-staleness
        # SLI's anchor: at solve time, staleness = now - last_event_ts.
        # A bare float write/read (GIL-atomic) — no lock on the
        # event-ingestion hot path.
        self.last_event_ts = 0.0
        self._nodes: Dict[str, _NodeInfoListItem] = {}
        self._head: Optional[_NodeInfoListItem] = None
        self._node_tree = NodeTree()
        self._assumed_pods: Set[str] = set()
        self._pod_states: Dict[str, _PodState] = {}
        self._image_states: Dict[str, _ImageState] = {}
        self._stop = threading.Event()
        self._cleanup_thread: Optional[threading.Thread] = None
        # device-mirror delta journal (ops.mirror.DeltaJournal): when
        # attached, every mutation_seq bump is noted as a compact delta
        # record so the solver session can SCATTER the window into the
        # device-resident planes instead of rebuilding. None (default)
        # costs one attribute test per mutation.
        self._journal = None

    # ------------------------------------------------------------------
    # linked-list maintenance (cache.go moveNodeInfoToHead / removeNodeInfoFromList)
    def _move_to_head(self, name: str) -> None:
        item = self._nodes.get(name)
        if item is None or item is self._head:
            return
        if item.prev is not None:
            item.prev.next = item.next
        if item.next is not None:
            item.next.prev = item.prev
        if self._head is not None:
            self._head.prev = item
        item.next = self._head
        item.prev = None
        self._head = item

    def _remove_from_list(self, name: str) -> None:
        item = self._nodes.get(name)
        if item is None:
            return
        if item.prev is not None:
            item.prev.next = item.next
        if item.next is not None:
            item.next.prev = item.prev
        if item is self._head:
            self._head = item.next
        del self._nodes[name]

    def _ensure_node(self, name: str) -> _NodeInfoListItem:
        item = self._nodes.get(name)
        if item is None:
            item = _NodeInfoListItem(NodeInfo())
            self._nodes[name] = item
            if self._head is not None:
                self._head.prev = item
            item.next = self._head
            self._head = item
        return item

    # ------------------------------------------------------------------
    def attach_delta_journal(self, journal) -> None:
        """Attach a mirror delta journal: every later ``mutation_seq``
        bump emits one record (under the cache lock, so record order ==
        seq order). Re-attaching replaces the journal — an earlier
        session's journal simply stops receiving records and its next
        window read reports a gap (→ reseed)."""
        with self._lock:
            self._journal = journal

    def _note(self, kind: str, a=None, b=None) -> None:
        """Journal the mutation just bumped (caller holds the lock and
        has ALREADY incremented ``_mutation_seq``)."""
        if self._journal is not None:
            self._journal.note(self._mutation_seq, kind, a, b)

    # ------------------------------------------------------------------
    @property
    def mutation_seq(self) -> int:
        with self._lock:
            return self._mutation_seq

    @property
    def node_set_seq(self) -> int:
        with self._lock:
            return self._node_set_seq

    def commit_target_flags(self, names) -> Dict[str, Optional[Node]]:
        """Commit-time liveness probe for a batch of bind targets: ONE
        lock acquisition for the whole batch, set lookups per name.
        Returns ONLY the suspect entries — ``name -> None`` when the
        node is gone from the cache (deleted, or never seen), ``name ->
        Node`` when it exists but is cordoned or carries taints the
        commit guard must test against the pod's tolerations. Names
        absent from the result are fully bindable. The common no-churn
        batch returns an empty dict, so the guard costs O(1) per commit
        and nothing allocates on the happy path."""
        flagged: Dict[str, Optional[Node]] = {}
        with self._lock:
            for name in names:
                item = self._nodes.get(name)
                node = item.info.node if item is not None else None
                if node is None:
                    flagged[name] = None
                elif node.spec.unschedulable or any(
                    t.key == TAINT_NODE_UNREACHABLE
                    for t in node.spec.taints
                ):
                    flagged[name] = node
        return flagged

    def commit_fits(self, items) -> List[Optional[str]]:
        """Commit-time capacity probe for a batch of (pod, node_name)
        bind targets — the multi-replica conflict guard's cache half:
        a replica about to commit checks that each target still has
        room AGAINST THE LIVE CACHE, which by now includes the pods its
        sibling replicas bound since this batch was solved (their bind
        events apply to every replica's cache). Cumulative within the
        batch (two pods of this batch on one node charge it twice).
        Returns a positional reason-or-None list; node existence and
        taint staleness remain ``commit_target_flags``'s job."""
        from kubernetes_tpu.scheduler.types import (
            compute_pod_resource_request,
        )

        out: List[Optional[str]] = [None] * len(items)
        with self._lock:
            extra: Dict[str, List[int]] = {}
            for i, (pod, node_name) in enumerate(items):
                item = self._nodes.get(node_name)
                if item is None or item.info.node is None:
                    continue
                info = item.info
                req = compute_pod_resource_request(pod)
                add = extra.setdefault(node_name, [0, 0, 0])
                alloc = info.allocatable
                if (alloc.milli_cpu and info.requested.milli_cpu + add[0]
                        + req.milli_cpu > alloc.milli_cpu) or \
                   (alloc.memory and info.requested.memory + add[1]
                        + req.memory > alloc.memory) or \
                   (alloc.allowed_pod_number and len(info.pods) + add[2]
                        + 1 > alloc.allowed_pod_number):
                    out[i] = "capacity"
                    continue
                add[0] += req.milli_cpu
                add[1] += req.memory
                add[2] += 1
        return out

    def note_external_mutation(self) -> None:
        """Record a state change the cache itself doesn't track (PV /
        PVC / StorageClass / CSINode / Service object churn). The batch
        sidecar's device mirror encodes volume feasibility and attach
        budgets from those objects, so their mutations must invalidate
        the mirror exactly like pod/node mutations do — the bump makes
        ``SolverSession.mirror_current``'s arithmetic fail."""
        with self._lock:
            self._mutation_seq += 1
            self._note("external")

    def note_event_ts(self, ts: float) -> None:
        """Advance the newest-applied-event commit timestamp (called by
        the event handlers once per delivered batch; monotonic by
        construction, but a relist can replay out of order — keep the
        max)."""
        if ts > self.last_event_ts:
            self.last_event_ts = ts

    # ------------------------------------------------------------------
    # pods
    def assume_pod(self, pod: Pod) -> None:
        key = get_pod_key(pod)
        with self._lock:
            if key in self._pod_states:
                raise ValueError(f"pod {key} is in the cache, so can't be assumed")
            self._mutation_seq += 1
            # serial-path bind: NOT device-applied (the solve carry
            # never placed this pod) — the mirror scatters it
            self._note("assume", pod)
            self._add_pod_locked(pod)
            self._pod_states[key] = _PodState(pod)
            self._assumed_pods.add(key)

    def assume_pods(self, pods: List[Pod]) -> List[Optional[str]]:
        """Bulk assume under ONE lock (the batch commit path). Returns a
        positional list of error messages (None = assumed). Semantics are
        exactly N sequential ``assume_pod`` calls: one mutation_seq bump
        per successful assume, per-pod already-cached failures."""
        errors: List[Optional[str]] = [None] * len(pods)
        with self._lock:
            for i, pod in enumerate(pods):
                key = get_pod_key(pod)
                if key in self._pod_states:
                    errors[i] = (
                        f"pod {key} is in the cache, so can't be assumed"
                    )
                    continue
                self._mutation_seq += 1
                # bulk-commit assume: the solve carry already applied
                # this pod on device — the mirror must NOT re-scatter
                self._note("assume_bulk", pod)
                self._add_pod_locked(pod)
                self._pod_states[key] = _PodState(pod)
                self._assumed_pods.add(key)
        return errors

    def add_pods(self, pods: List[Pod]) -> None:
        """Bulk informer-confirmed adds under one lock (the batched
        bind-transition delivery): same per-pod semantics as add_pod."""
        with self._lock:
            for pod in pods:
                self._add_pod_confirmed_locked(pod)

    def _add_pod_confirmed_locked(self, pod: Pod) -> None:
        key = get_pod_key(pod)
        if key in self._assumed_pods:
            state = self._pod_states[key]
            if state.pod.spec.node_name != pod.spec.node_name:
                # scheduler result differs from api truth: relocate
                self._mutation_seq += 1
                self._note("pod_move", state.pod, pod)
                self._remove_pod_locked(state.pod)
                self._add_pod_locked(pod)
            self._assumed_pods.discard(key)
            self._pod_states[key] = _PodState(pod)
        elif key in self._pod_states:
            # duplicate add: treat as update
            self._mutation_seq += 1
            self._note("pod_update", self._pod_states[key].pod, pod)
            self._update_pod_locked(self._pod_states[key].pod, pod)
            self._pod_states[key] = _PodState(pod)
        else:
            self._mutation_seq += 1
            self._note("pod_add", pod)
            self._add_pod_locked(pod)
            self._pod_states[key] = _PodState(pod)

    def finish_binding_many(self, pods: List[Pod],
                            now: Optional[float] = None) -> None:
        """Bulk finish_binding under one lock: starts the assumed-pod TTL
        for every pod in the committed batch."""
        deadline = (now if now is not None else self._now()) + self._ttl
        with self._lock:
            for pod in pods:
                key = get_pod_key(pod)
                state = self._pod_states.get(key)
                if state is not None and key in self._assumed_pods:
                    state.binding_finished = True
                    state.deadline = deadline

    def finish_binding(self, pod: Pod, now: Optional[float] = None) -> None:
        key = get_pod_key(pod)
        with self._lock:
            state = self._pod_states.get(key)
            if state is not None and key in self._assumed_pods:
                state.binding_finished = True
                state.deadline = (now if now is not None else self._now()) + self._ttl

    def forget_pod(self, pod: Pod) -> None:
        key = get_pod_key(pod)
        with self._lock:
            if key not in self._assumed_pods:
                raise ValueError(f"pod {key} wasn't assumed, so can't be forgotten")
            self._mutation_seq += 1
            self._note("pod_del", self._pod_states[key].pod)
            self._remove_pod_locked(self._pod_states[key].pod)
            del self._pod_states[key]
            self._assumed_pods.discard(key)

    def add_pod(self, pod: Pod) -> None:
        """Informer-confirmed pod add (cache.go AddPod)."""
        with self._lock:
            self._add_pod_confirmed_locked(pod)

    def update_pod(self, old: Pod, new: Pod) -> None:
        key = get_pod_key(old)
        with self._lock:
            if key in self._assumed_pods:
                raise ValueError(f"assumed pod {key} shouldn't be updated")
            if _pod_mirror_changed(old, new):
                self._mutation_seq += 1
                self._note("pod_update", old, new)
            self._update_pod_locked(old, new)
            self._pod_states[key] = _PodState(new)

    def remove_pod(self, pod: Pod) -> None:
        key = get_pod_key(pod)
        with self._lock:
            state = self._pod_states.get(key)
            if state is None:
                return
            self._mutation_seq += 1
            self._note("pod_del", state.pod)
            self._remove_pod_locked(state.pod)
            del self._pod_states[key]
            self._assumed_pods.discard(key)

    def get_pod(self, pod: Pod) -> Optional[Pod]:
        with self._lock:
            state = self._pod_states.get(get_pod_key(pod))
            return state.pod if state else None

    def is_assumed_pod(self, pod: Pod) -> bool:
        with self._lock:
            return get_pod_key(pod) in self._assumed_pods

    def pod_count(self) -> int:
        with self._lock:
            return sum(len(item.info.pods) for item in self._nodes.values())

    def _add_pod_locked(self, pod: Pod) -> None:
        item = self._ensure_node(pod.spec.node_name)
        item.info.add_pod(pod)
        self._move_to_head(pod.spec.node_name)

    def _remove_pod_locked(self, pod: Pod) -> None:
        item = self._nodes.get(pod.spec.node_name)
        if item is not None:
            item.info.remove_pod(pod)
            if item.info.node is None and not item.info.pods:
                self._remove_from_list(pod.spec.node_name)
            else:
                self._move_to_head(pod.spec.node_name)

    def _update_pod_locked(self, old: Pod, new: Pod) -> None:
        self._remove_pod_locked(old)
        self._add_pod_locked(new)

    # ------------------------------------------------------------------
    # nodes
    def add_node(self, node: Node) -> None:
        with self._lock:
            self._mutation_seq += 1
            item = self._ensure_node(node.name)
            if item.info.node is None:
                self._node_set_seq += 1
                self._note("node_add", node)
            else:
                # re-add of a known node is an update in mirror terms
                self._note("node_update", item.info.node, node)
            self._remove_node_image_states(item.info.node)
            item.info.set_node(node)
            self._add_node_image_states(node, item.info)
            self._node_tree.add_node(node)
            self._move_to_head(node.name)

    def update_node(self, old: Node, new: Node) -> None:
        with self._lock:
            if _node_mirror_changed(old, new):
                self._mutation_seq += 1
                self._note("node_update", old, new)
            item = self._ensure_node(new.name)
            self._remove_node_image_states(item.info.node)
            item.info.set_node(new)
            self._add_node_image_states(new, item.info)
            self._node_tree.update_node(old, new)
            self._move_to_head(new.name)

    def remove_node(self, node: Node) -> None:
        with self._lock:
            item = self._nodes.get(node.name)
            if item is None:
                return
            self._mutation_seq += 1
            self._note("node_del", node)
            if item.info.node is not None:
                self._node_set_seq += 1
            item.info.remove_node()
            self._remove_node_image_states(node)
            # keep the entry while pods remain (they'll be removed by events)
            if not item.info.pods:
                self._remove_from_list(node.name)
            else:
                self._move_to_head(node.name)
            self._node_tree.remove_node(node)

    def node_count(self) -> int:
        with self._lock:
            return self._node_tree.num_nodes

    def _add_node_image_states(self, node: Node, ni: NodeInfo) -> None:
        summaries: Dict[str, _ImageState] = {}
        for img in node.status.images:
            for name in img.names:
                state = self._image_states.get(name)
                if state is None:
                    state = _ImageState(img.size_bytes)
                    self._image_states[name] = state
                state.size = img.size_bytes
                state.nodes.add(node.name)
                summaries[name] = state
        ni.image_states = summaries

    def _remove_node_image_states(self, node: Optional[Node]) -> None:
        if node is None:
            return
        for img in node.status.images:
            for name in img.names:
                state = self._image_states.get(name)
                if state is not None:
                    state.nodes.discard(node.name)
                    if not state.nodes:
                        del self._image_states[name]

    # ------------------------------------------------------------------
    # snapshot
    def update_snapshot(self, snapshot: Snapshot) -> None:
        """Incremental O(changed-nodes) update (cache.go:203-287): walk the
        generation-ordered list from the head, stop at the first item whose
        generation the snapshot has already seen."""
        with self._lock:
            balanced_generation = 0
            update_all_lists = False
            updated_affinity = False

            item = self._head
            while item is not None and item.info.generation > snapshot.generation:
                info = item.info
                name = info.node.name if info.node is not None else None
                if name is None:
                    item = item.next
                    continue
                if balanced_generation == 0:
                    # generation of the most recently updated node
                    balanced_generation = info.generation
                existing = snapshot.node_info_map.get(name)
                if existing is None:
                    update_all_lists = True
                    snapshot.node_info_map[name] = info.clone()
                else:
                    if (
                        bool(existing.pods_with_affinity)
                        != bool(info.pods_with_affinity)
                        or bool(existing.pods_with_required_anti_affinity)
                        != bool(info.pods_with_required_anti_affinity)
                    ):
                        updated_affinity = True
                    # copy IN PLACE: the snapshot's ordered lists hold the
                    # same NodeInfo objects as the map
                    existing.copy_from(info)
                item = item.next

            if balanced_generation:
                snapshot.generation = balanced_generation
            elif self._head is not None:
                snapshot.generation = max(
                    snapshot.generation, self._head.info.generation
                )

            # Reconcile deletions only when the snapshot can have shrunk
            # (cache.go guards with len(snapshot map) > nodeTree.numNodes —
            # a removal leaves the map larger than the live-node count, so
            # the common no-deletion cycle stays O(changed prefix)).
            if len(snapshot.node_info_map) > self._node_tree.num_nodes:
                live = {
                    n
                    for n, it in self._nodes.items()
                    if it.info.node is not None
                }
                for name in [n for n in snapshot.node_info_map if n not in live]:
                    del snapshot.node_info_map[name]
                update_all_lists = True

            if update_all_lists or updated_affinity or len(
                snapshot.node_info_list
            ) != len(snapshot.node_info_map):
                self._update_snapshot_lists(snapshot)

    def _update_snapshot_lists(self, snapshot: Snapshot) -> None:
        """Rebuild ordered lists in zone-interleaved node_tree order
        (cache.go:289 updateNodeInfoSnapshotList)."""
        order = self._node_tree.list()
        snapshot.node_info_list = [
            snapshot.node_info_map[n] for n in order if n in snapshot.node_info_map
        ]
        snapshot.have_pods_with_affinity_node_info_list = [
            ni for ni in snapshot.node_info_list if ni.pods_with_affinity
        ]
        snapshot.have_pods_with_required_anti_affinity_node_info_list = [
            ni for ni in snapshot.node_info_list if ni.pods_with_required_anti_affinity
        ]

    # ------------------------------------------------------------------
    # dump (debugger support) and expiry
    def dump(self):
        with self._lock:
            return {
                "nodes": {
                    n: item.info.clone() for n, item in self._nodes.items()
                },
                "assumed_pods": set(self._assumed_pods),
            }

    def run(self) -> None:
        """Start the assumed-pod expiry goroutine-equivalent (cache.go:42)."""
        if self._cleanup_thread is not None:
            return
        self._cleanup_thread = threading.Thread(
            target=self._cleanup_loop, daemon=True, name="cache-expiry"
        )
        self._cleanup_thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _cleanup_loop(self) -> None:
        while not self._stop.wait(CLEANUP_INTERVAL):
            self.cleanup_expired_assumed_pods()

    def cleanup_expired_assumed_pods(self, now: Optional[float] = None) -> None:
        now = now if now is not None else self._now()
        with self._lock:
            for key in list(self._assumed_pods):
                state = self._pod_states.get(key)
                if state is None:
                    self._assumed_pods.discard(key)
                    continue
                if state.binding_finished and state.deadline is not None and now >= state.deadline:
                    # expire: the bind never became visible; undo the assume
                    self._mutation_seq += 1
                    self._note("pod_del", state.pod)
                    self._remove_pod_locked(state.pod)
                    del self._pod_states[key]
                    self._assumed_pods.discard(key)
