"""Vectorized preemption candidate screening for the batch path.

Under mass decline (thousands of unschedulable pods per solved batch),
running the reference's preemption dry-run over its sampled ~max(10% of
nodes, 100) candidates PER POD is quadratic — the dry-run clones node
state and re-runs the full filter chain per candidate
(``default_preemption.go:328 dryRunPreemption``). This module is the
"device-assisted candidate pruning" half of the batch design: one
columnar screen per batch computes, for every declined pod at once,

    fits_after_removal[p, n] =
        request[p] <= allocatable[n] - requested[n] + freeable[prio(p), n]

where ``freeable[t, n]`` sums the requests of node ``n``'s pods with
priority `` < t`` (victims a preemptor at priority ``t`` may evict), and
ranks each pod's feasible nodes by fewest victims, then most free margin.
The ranked top-K go to ``DefaultPreemption`` as CANDIDATE HINTS — the
dry-run still validates every hinted node with the full filter chain (and
PDB split) before victims are selected, so the screen only prunes, never
decides. Pods whose screen comes up empty fall back to the unpruned scan.

The screen is advisory and deliberately coarse: cpu + memory only
(extended resources, ports, and topology effects are the dry-run's job),
and it is built once per commit batch — preemptions landing mid-batch
may invalidate a hint, which the dry-run then rejects.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from kubernetes_tpu.scheduler.types import compute_pod_resource_request


class PreemptionScreen:
    """One batch's columnar screen over the live snapshot."""

    def __init__(self, node_infos):
        node_infos = [ni for ni in node_infos if ni.node is not None]
        self.node_names = [ni.node.name for ni in node_infos]
        n = len(node_infos)
        self.alloc = np.zeros((n, 2), dtype=np.int64)
        self.requested = np.zeros((n, 2), dtype=np.int64)
        # distinct victim priorities present, ascending; freeable/victims
        # are cumulative-exclusive per threshold: threshold index t
        # covers preemptors whose priority is > prios[t]
        prio_set = set()
        for ni in node_infos:
            for pi in ni.pods:
                prio_set.add(pi.pod.priority())
        self.prios = sorted(prio_set)
        p = len(self.prios)
        self.freeable = np.zeros((p, n, 2), dtype=np.int64)
        self.victims = np.zeros((p, n), dtype=np.int32)
        prio_index = {v: i for i, v in enumerate(self.prios)}
        for j, ni in enumerate(node_infos):
            self.alloc[j, 0] = ni.allocatable.milli_cpu
            self.alloc[j, 1] = ni.allocatable.memory
            self.requested[j, 0] = ni.requested.milli_cpu
            self.requested[j, 1] = ni.requested.memory
            for pi in ni.pods:
                req = compute_pod_resource_request(pi.pod)
                i = prio_index[pi.pod.priority()]
                self.freeable[i, j, 0] += req.milli_cpu
                self.freeable[i, j, 1] += req.memory
                self.victims[i, j] += 1
        # prefix-sum over ascending priority: row t now holds totals for
        # pods with priority <= prios[t]
        np.cumsum(self.freeable, axis=0, out=self.freeable)
        np.cumsum(self.victims, axis=0, out=self.victims)
        self.free = self.alloc - self.requested  # [N, 2]

    def _threshold_row(self, preemptor_priority: int) -> Optional[int]:
        """Largest index t with prios[t] < preemptor_priority, or None
        when no pod anywhere has lower priority."""
        import bisect

        t = bisect.bisect_left(self.prios, preemptor_priority) - 1
        return t if t >= 0 else None

    def candidates_for(self, pod, k: int = 16, static_mask=None,
                       rotation: int = 0) -> List[str]:
        """Ranked candidate node names for ``pod`` (top-``k``): nodes
        where the pod fits once every lower-priority pod is removed,
        fewest victims first, then most free margin. ``static_mask``
        (bool [N], True = node passes the pod's node-static predicates)
        prunes nodes the dry-run could never accept.

        ``rotation`` spreads a BATCH of equally-shaped preemptors over
        distinct candidates (the analog of upstream's random dry-run
        offset, ``default_preemption.go:195``): without it every
        declined pod of a uniform batch receives the identical ranked
        list, they all chase the same few nodes' victims, and everyone
        after the first finds stale hints and falls back to the full
        candidate scan."""
        t = self._threshold_row(pod.priority())
        if t is None:
            return []
        req = compute_pod_resource_request(pod)
        need = np.array([req.milli_cpu, req.memory], dtype=np.int64)
        headroom = self.free + self.freeable[t]          # [N, 2]
        fits = np.all(headroom >= need[None, :], axis=1)
        fits &= self.victims[t] > 0  # a candidate must have victims
        if static_mask is not None:
            m = np.asarray(static_mask, dtype=bool)
            if m.shape[0] >= fits.shape[0]:
                fits &= m[: fits.shape[0]]
        idx = np.nonzero(fits)[0]
        if idx.size == 0:
            return []
        vic = self.victims[t][idx].astype(np.int64)
        margin = np.min(headroom[idx] - need[None, :], axis=1)
        # fewest victims, then largest margin (stable, deterministic)
        order = np.lexsort((-margin, vic))
        if rotation and idx.size > k:
            order = np.roll(order, -(rotation % idx.size))
        return [self.node_names[i] for i in idx[order[:k]]]


def build_screen(snapshot) -> Optional[PreemptionScreen]:
    """Build a screen from the live snapshot; None on empty clusters."""
    node_infos = snapshot.list()
    if not node_infos:
        return None
    return PreemptionScreen(node_infos)
