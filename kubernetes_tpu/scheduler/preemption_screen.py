"""Vectorized preemption candidate screening for the batch path.

Under mass decline (thousands of unschedulable pods per solved batch),
running the reference's preemption dry-run over its sampled ~max(10% of
nodes, 100) candidates PER POD is quadratic — the dry-run clones node
state and re-runs the full filter chain per candidate
(``default_preemption.go:328 dryRunPreemption``). This module is the
"device-assisted candidate pruning" half of the batch design: one
columnar screen per batch computes, for every declined pod at once,

    fits_after_removal[p, n] =
        request[p] <= allocatable[n] - requested[n] + freeable[prio(p), n]

where ``freeable[t, n]`` sums the requests of node ``n``'s pods with
priority `` < t`` (victims a preemptor at priority ``t`` may evict), and
ranks each pod's feasible nodes by fewest victims, then most free margin.
The ranked top-K go to ``DefaultPreemption`` as CANDIDATE HINTS — the
dry-run still validates every hinted node with the full filter chain (and
PDB split) before victims are selected, so the screen only prunes, never
decides. Pods whose screen comes up empty fall back to the unpruned scan.

The screen is advisory and deliberately coarse: cpu + memory only
(extended resources, ports, and topology effects are the dry-run's job),
and it is built once per commit batch — preemptions landing mid-batch
may invalidate a hint, which the dry-run then rejects.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from kubernetes_tpu.scheduler.types import compute_pod_resource_request


class PreemptionScreen:
    """One batch's columnar screen over the live snapshot."""

    def __init__(self, node_infos):
        node_infos = [ni for ni in node_infos if ni.node is not None]
        self.node_names = [ni.node.name for ni in node_infos]
        n = len(node_infos)
        self.alloc = np.zeros((n, 2), dtype=np.int64)
        self.requested = np.zeros((n, 2), dtype=np.int64)
        # distinct victim priorities present, ascending; freeable/victims
        # are cumulative-exclusive per threshold: threshold index t
        # covers preemptors whose priority is > prios[t]
        prio_set = set()
        for ni in node_infos:
            for pi in ni.pods:
                prio_set.add(pi.pod.priority())
        self.prios = sorted(prio_set)
        p = len(self.prios)
        self.freeable = np.zeros((p, n, 2), dtype=np.int64)
        self.victims = np.zeros((p, n), dtype=np.int32)
        prio_index = {v: i for i, v in enumerate(self.prios)}
        for j, ni in enumerate(node_infos):
            self.alloc[j, 0] = ni.allocatable.milli_cpu
            self.alloc[j, 1] = ni.allocatable.memory
            self.requested[j, 0] = ni.requested.milli_cpu
            self.requested[j, 1] = ni.requested.memory
            for pi in ni.pods:
                req = compute_pod_resource_request(pi.pod)
                i = prio_index[pi.pod.priority()]
                self.freeable[i, j, 0] += req.milli_cpu
                self.freeable[i, j, 1] += req.memory
                self.victims[i, j] += 1
        # prefix-sum over ascending priority: row t now holds totals for
        # pods with priority <= prios[t]
        np.cumsum(self.freeable, axis=0, out=self.freeable)
        np.cumsum(self.victims, axis=0, out=self.victims)
        self.free = self.alloc - self.requested  # [N, 2]

    def _threshold_row(self, preemptor_priority: int) -> Optional[int]:
        """Largest index t with prios[t] < preemptor_priority, or None
        when no pod anywhere has lower priority."""
        import bisect

        t = bisect.bisect_left(self.prios, preemptor_priority) - 1
        return t if t >= 0 else None

    def candidates_for(self, pod, k: int = 16, static_mask=None,
                       rotation: int = 0) -> List[str]:
        """Ranked candidate node names for ``pod`` (top-``k``): nodes
        where the pod fits once every lower-priority pod is removed,
        fewest victims first, then most free margin. ``static_mask``
        (bool [N], True = node passes the pod's node-static predicates)
        prunes nodes the dry-run could never accept.

        ``rotation`` spreads a BATCH of equally-shaped preemptors over
        distinct candidates (the analog of upstream's random dry-run
        offset, ``default_preemption.go:195``): without it every
        declined pod of a uniform batch receives the identical ranked
        list, they all chase the same few nodes' victims, and everyone
        after the first finds stale hints and falls back to the full
        candidate scan."""
        t = self._threshold_row(pod.priority())
        if t is None:
            return []
        req = compute_pod_resource_request(pod)
        need = np.array([req.milli_cpu, req.memory], dtype=np.int64)
        headroom = self.free + self.freeable[t]          # [N, 2]
        fits = np.all(headroom >= need[None, :], axis=1)
        fits &= self.victims[t] > 0  # a candidate must have victims
        if static_mask is not None:
            m = np.asarray(static_mask, dtype=bool)
            if m.shape[0] >= fits.shape[0]:
                fits &= m[: fits.shape[0]]
        idx = np.nonzero(fits)[0]
        if idx.size == 0:
            return []
        vic = self.victims[t][idx].astype(np.int64)
        margin = np.min(headroom[idx] - need[None, :], axis=1)
        # fewest victims, then largest margin (stable, deterministic)
        order = np.lexsort((-margin, vic))
        if rotation and idx.size > k:
            order = np.roll(order, -(rotation % idx.size))
        return [self.node_names[i] for i in idx[order[:k]]]


def build_screen(snapshot) -> Optional[PreemptionScreen]:
    """Build a screen from the live snapshot; None on empty clusters."""
    node_infos = snapshot.list()
    if not node_infos:
        return None
    return PreemptionScreen(node_infos)


_HUGE_PRIO = np.int64(2**62)


class VictimPlanner:
    """Batch preemption planning from per-(node, priority) SORTED victim
    prefix sums (VERDICT r2 #3: the victim-selection half moves off the
    per-candidate clone+refilter dry-run).

    Per node, pods are ordered by ascending priority with cumulative
    cpu/memory sums; a preemptor at priority ``P`` needing ``need``
    takes the MINIMAL victim prefix ``k`` with

        free[n] + cum[n, o+k-1] - cum[n, o-1] >= need,   prios < P

    which is exactly the victim set the reference's reprieve loop
    converges to under resource constraints (remove everything, re-add
    by DESCENDING priority while filters pass → the lowest-priority
    prefix remains evicted, ``default_preemption.go:600,650``).
    Topology/affinity effects are NOT modeled: the caller validates
    every plan with the real filter chain post-deletion and falls back
    to the standard PostFilter flow when validation fails.

    Planning is stateful across one batch: consumed victims advance the
    node's offset and ``free`` tracks both evictions and planned
    placements, so a batch of preemptors never double-claims a victim.
    Any pod COVERED by a PodDisruptionBudget — regardless of remaining
    budget — is excluded at build time: one planned batch could
    otherwise burn through a budget the serial path (which re-reads
    budgets per cycle) would respect after the first disruption.
    PDB-covered victims belong to the standard dry-run flow, whose
    reprieve logic owns violation counting and ordering.
    """

    def __init__(self, node_infos, pdbs=()):
        node_infos = [ni for ni in node_infos if ni.node is not None]
        self.node_names = [ni.node.name for ni in node_infos]
        n = len(node_infos)
        self.alloc = np.zeros((n, 2), dtype=np.int64)
        requested = np.zeros((n, 2), dtype=np.int64)
        self.pod_room = np.zeros(n, dtype=np.int64)  # max_pods - count
        per_node: List[List] = []
        vmax = 1
        for j, ni in enumerate(node_infos):
            self.alloc[j, 0] = ni.allocatable.milli_cpu
            self.alloc[j, 1] = ni.allocatable.memory
            requested[j, 0] = ni.requested.milli_cpu
            requested[j, 1] = ni.requested.memory
            self.pod_room[j] = (
                (ni.allocatable.allowed_pod_number or 1_000_000)
                - len(ni.pods)
            )
            victims = [
                pi.pod for pi in ni.pods
                if pi.pod.metadata.deletion_timestamp is None
                and not _covered_by_pdb(pi.pod, pdbs)
            ]
            victims.sort(key=lambda p: p.priority())
            per_node.append(victims)
            vmax = max(vmax, len(victims))
        self.free = self.alloc - requested                   # [N, 2]
        self.v_pods = per_node
        self.v_prio = np.full((n, vmax), _HUGE_PRIO, dtype=np.int64)
        res = np.zeros((n, vmax, 2), dtype=np.int64)
        for j, victims in enumerate(per_node):
            for i, pod in enumerate(victims):
                self.v_prio[j, i] = pod.priority()
                req = compute_pod_resource_request(pod)
                res[j, i, 0] = req.milli_cpu
                res[j, i, 1] = req.memory
        self.cum = np.cumsum(res, axis=1)                    # [N, V, 2]
        self.consumed = np.zeros(n, dtype=np.int64)          # offset o
        # bumped per placement; stales lazy heap entries in plan_group
        self._version = np.zeros(n, dtype=np.int64)

    def _node_proposal(self, n: int, p: int, need) -> Optional[tuple]:
        """(k, margin) for placing one preemptor at priority ``p`` on
        node ``n``, or None when infeasible. O(V) — the incremental
        half of the heap allocator."""
        o = int(self.consumed[n])
        free = self.free[n]
        vmax = self.cum.shape[1]
        if free[0] >= need[0] and free[1] >= need[1]:
            k = 0
            freed0 = freed1 = 0
        else:
            base0 = self.cum[n, o - 1, 0] if o > 0 else 0
            base1 = self.cum[n, o - 1, 1] if o > 0 else 0
            j0 = int(np.searchsorted(self.cum[n, :, 0],
                                     need[0] - free[0] + base0))
            j1 = int(np.searchsorted(self.cum[n, :, 1],
                                     need[1] - free[1] + base1))
            j = max(j0, j1)
            if j >= vmax or self.v_prio[n, j] >= p:
                return None
            k = j - o + 1
            if k < 1:
                return None
            freed0 = int(self.cum[n, j, 0]) - base0
            freed1 = int(self.cum[n, j, 1]) - base1
        if self.pod_room[n] + k < 1:
            return None
        margin = min(int(free[0]) + freed0 - int(need[0]),
                     int(free[1]) + freed1 - int(need[1]))
        return k, margin

    def plan_group(self, pod, count: int, static_mask=None):
        """Plan up to ``count`` preemptors SHAPED LIKE ``pod`` (same
        priority/requests/static profile — mass-decline batches are
        dominated by such runs) in one pass: one vectorized feasibility
        sweep builds a (victims, -margin) heap over nodes; each
        placement then re-scores only its node in O(V). Returns a list
        of (node_name, victims) with length <= count; the caller maps
        them onto its pods in batch order. Mutates planner state."""
        import heapq

        n = len(self.node_names)
        if n == 0 or count <= 0:
            return []
        p = pod.priority()
        req = compute_pod_resource_request(pod)
        need = np.array([req.milli_cpu, req.memory], dtype=np.int64)
        o = self.consumed
        idx = np.arange(n)
        base = np.where(
            (o > 0)[:, None],
            self.cum[idx, np.maximum(o - 1, 0)], 0,
        )                                                    # [N, 2]
        elig_total = np.sum(self.v_prio < p, axis=1)         # [N]
        target = need[None, :] - self.free + base            # [N, 2]
        j_dim = np.empty((n, 2), dtype=np.int64)
        for d in (0, 1):
            j_dim[:, d] = (self.cum[:, :, d] < target[:, d:d + 1]).sum(1)
        j = np.max(j_dim, axis=1)                            # [N]
        k = j - o + 1
        fits_now = np.all(self.free >= need[None, :], axis=1)
        k = np.where(fits_now, 0, k)
        feasible = fits_now | (
            (k >= 1) & (j < elig_total) & (j < self.cum.shape[1])
        )
        feasible &= (self.pod_room + k) >= 1
        if static_mask is not None:
            m = np.asarray(static_mask, dtype=bool)
            if m.shape[0] >= n:
                feasible &= m[:n]
        cand = np.nonzero(feasible)[0]
        if cand.size == 0:
            return []
        jj = np.minimum(j[cand], self.cum.shape[1] - 1)
        freed = np.where(
            (k[cand] > 0)[:, None],
            self.cum[cand, jj] - base[cand], 0,
        )
        margin = np.min(self.free[cand] + freed - need[None, :], axis=1)
        # lazy-invalidation heap: entries carry the node's version at
        # push time; placements bump the version, staling old entries
        heap = [
            (int(k[c]), -int(margin[i]), int(c), int(self._version[c]))
            for i, c in enumerate(cand)
        ]
        heapq.heapify(heap)
        plans = []
        while heap and len(plans) < count:
            kk, neg_margin, node, ver = heapq.heappop(heap)
            if ver != self._version[node]:
                prop = self._node_proposal(node, p, need)
                if prop is not None:
                    heapq.heappush(heap, (
                        prop[0], -prop[1], node,
                        int(self._version[node]),
                    ))
                continue
            oo = int(self.consumed[node])
            victims = self.v_pods[node][oo: oo + kk]
            if kk > 0:
                b0 = self.cum[node, oo - 1] if oo > 0 else 0
                self.free[node] += self.cum[node, oo + kk - 1] - b0
            self.consumed[node] += kk
            self.free[node] -= need
            self.pod_room[node] += kk - 1
            self._version[node] += 1
            plans.append((self.node_names[node], victims))
            prop = self._node_proposal(node, p, need)
            if prop is not None:
                heapq.heappush(heap, (
                    prop[0], -prop[1], node, int(self._version[node]),
                ))
        return plans


def _covered_by_pdb(pod, pdbs) -> bool:
    from kubernetes_tpu.scheduler.framework.plugins.default_preemption import (
        pdb_covers,
    )

    return any(pdb_covers(pod, pdb) for pdb in pdbs)


def build_victim_planner(snapshot, pdbs=()) -> Optional[VictimPlanner]:
    node_infos = snapshot.list()
    if not node_infos:
        return None
    return VictimPlanner(node_infos, pdbs=pdbs)
