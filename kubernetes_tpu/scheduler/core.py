"""Generic scheduling algorithm (reference
``pkg/scheduler/core/generic_scheduler.go``): snapshot → PreFilter →
parallel Filter with adaptive node sampling and round-robin fairness →
extender filter → PreScore/Score → extender prioritize → selectHost.

The adaptive ``percentageOfNodesToScore`` (:179-199 — ``50 − nodes/125``,
floor 5%, min 100 nodes) and the round-robin ``next_start_node_index``
(:302) are kept for host-path parity; the TPU batch path deliberately
evaluates **all** nodes densely instead (SURVEY.md section 2.5).
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework import interface as fw
from kubernetes_tpu.scheduler.framework.cycle_state import CycleState
from kubernetes_tpu.scheduler.framework.runtime import Framework
from kubernetes_tpu.scheduler.snapshot import Snapshot
from kubernetes_tpu.scheduler.types import NodeInfo
from kubernetes_tpu.utils.trace import Trace

MIN_FEASIBLE_NODES_TO_FIND = 100
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5


@dataclass
class ScheduleResult:
    suggested_host: str
    evaluated_nodes: int
    feasible_nodes: int


class GenericScheduler:
    def __init__(
        self,
        cache,
        extenders=(),
        percentage_of_nodes_to_score: int = 0,
        feature_gates=None,
    ):
        self.cache = cache
        self.extenders = list(extenders)
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        self.feature_gates = feature_gates
        self.snapshot = Snapshot()
        self.next_start_node_index = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def update_snapshot(self) -> None:
        self.cache.update_snapshot(self.snapshot)

    def schedule(
        self, state: CycleState, fwk: Framework, pod: Pod
    ) -> ScheduleResult:
        """Reference Schedule (generic_scheduler.go:97-146). Raises FitError
        when no node fits."""
        trace = Trace("Scheduling", pod=pod.full_name(), uid=pod.uid)
        # finally, not just the success exits: a FitError attempt is
        # exactly the slow, retried case a postmortem wants to see —
        # it must still reach the threshold log and the flight recorder
        try:
            self.update_snapshot()
            trace.step("Snapshotting scheduler cache and node infos done")
            if self.snapshot.num_nodes() == 0:
                raise fw.FitError(pod=pod, num_all_nodes=0)

            feasible, statuses = self.find_nodes_that_fit_pod(state, fwk,
                                                              pod)
            trace.step("Computing predicates done")
            if not feasible:
                raise fw.FitError(
                    pod=pod,
                    num_all_nodes=self.snapshot.num_nodes(),
                    filtered_nodes_statuses=statuses,
                )
            if len(feasible) == 1:
                return ScheduleResult(
                    feasible[0].node.name,
                    self.snapshot.num_nodes(),
                    1,
                )

            priority_list = self.prioritize_nodes(state, fwk, pod, feasible)
            trace.step("Prioritizing done")
            host = self.select_host(priority_list)
            trace.step("Selecting host done")
            return ScheduleResult(host, self.snapshot.num_nodes(),
                                  len(feasible))
        finally:
            trace.log_if_long(0.1)

    # ------------------------------------------------------------------
    def num_feasible_nodes_to_find(self, num_all_nodes: int) -> int:
        """generic_scheduler.go:179-199."""
        if (
            num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND
            or self.percentage_of_nodes_to_score >= 100
        ):
            return num_all_nodes
        adaptive = self.percentage_of_nodes_to_score
        if adaptive <= 0:
            adaptive = 50 - num_all_nodes // 125
            if adaptive < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
                adaptive = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
        num = num_all_nodes * adaptive // 100
        return max(num, MIN_FEASIBLE_NODES_TO_FIND)

    def find_nodes_that_fit_pod(
        self, state: CycleState, fwk: Framework, pod: Pod
    ) -> Tuple[List[NodeInfo], fw.NodeToStatusMap]:
        """generic_scheduler.go:223 findNodesThatFitPod."""
        statuses: fw.NodeToStatusMap = {}
        status = fwk.run_pre_filter_plugins(state, pod)
        if not fw.Status.is_ok(status):
            if status.is_unschedulable():
                for ni in self.snapshot.list():
                    if ni.node is not None:
                        statuses[ni.node.name] = status
                return [], statuses
            raise status.as_error()

        # PreferNominatedNode fast path (generic_scheduler.go:250, gated)
        if (
            self.feature_gates is not None
            and self.feature_gates.enabled("PreferNominatedNode")
            and pod.status.nominated_node_name
        ):
            ni = self.snapshot.get(pod.status.nominated_node_name)
            if ni is not None and ni.node is not None:
                s = fwk.run_filter_plugins_with_nominated_pods(state, pod, ni)
                if fw.Status.is_ok(s):
                    feasible, failed = self._extender_filter(pod, [ni], statuses)
                    if feasible:
                        return feasible, statuses

        feasible = self._find_nodes_that_pass_filters(state, fwk, pod, statuses)
        feasible, statuses = self._extender_filter(pod, feasible, statuses)
        return feasible, statuses

    def _find_nodes_that_pass_filters(
        self, state: CycleState, fwk: Framework, pod: Pod,
        statuses: fw.NodeToStatusMap,
    ) -> List[NodeInfo]:
        """generic_scheduler.go:273-345: round-robin start index, parallel
        per-node filter chain, early cancel once enough feasible nodes."""
        all_nodes = self.snapshot.list()
        num_all = len(all_nodes)
        num_to_find = self.num_feasible_nodes_to_find(num_all)

        if not fwk.has_filter_plugins():
            selected = [
                all_nodes[(self.next_start_node_index + i) % num_all]
                for i in range(num_to_find)
            ]
            self.next_start_node_index = (
                self.next_start_node_index + num_to_find
            ) % num_all
            return selected

        feasible: List[NodeInfo] = []
        lock = threading.Lock()
        stop = [False]
        processed = [0]

        def check(i: int) -> None:
            ni = all_nodes[(self.next_start_node_index + i) % num_all]
            status = fwk.run_filter_plugins_with_nominated_pods(state, pod, ni)
            with lock:
                processed[0] += 1
                if fw.Status.is_ok(status):
                    if len(feasible) < num_to_find:
                        feasible.append(ni)
                    if len(feasible) >= num_to_find:
                        stop[0] = True
                elif ni.node is not None:
                    statuses[ni.node.name] = status

        fwk.parallelizer.until(num_all, check, stop_check=lambda: stop[0])
        self.next_start_node_index = (
            self.next_start_node_index + processed[0]
        ) % num_all
        return feasible

    def _extender_filter(
        self, pod: Pod, feasible: List[NodeInfo], statuses: fw.NodeToStatusMap
    ) -> Tuple[List[NodeInfo], fw.NodeToStatusMap]:
        """generic_scheduler.go:347 findNodesThatPassExtenders: sequential."""
        for ext in self.extenders:
            if not feasible:
                break
            if not ext.is_interested(pod):
                continue
            try:
                feasible, failed = ext.filter(pod, feasible)
            except Exception as e:
                if ext.is_ignorable():
                    continue
                raise
            for name, reason in failed.items():
                statuses[name] = fw.Status(fw.UNSCHEDULABLE, reason)
        return feasible, statuses

    # ------------------------------------------------------------------
    def prioritize_nodes(
        self, state: CycleState, fwk: Framework, pod: Pod,
        nodes: List[NodeInfo],
    ) -> List[fw.NodeScore]:
        """generic_scheduler.go:405 prioritizeNodes."""
        node_names = [ni.node.name for ni in nodes]
        if not fwk.has_score_plugins() and not self.extenders:
            return [fw.NodeScore(n, 1) for n in node_names]

        status = fwk.run_pre_score_plugins(state, pod, nodes)
        if not fw.Status.is_ok(status):
            raise status.as_error()
        plugin_scores, status = fwk.run_score_plugins(state, pod, node_names)
        if not fw.Status.is_ok(status):
            raise status.as_error()

        totals: Dict[str, int] = {n: 0 for n in node_names}
        for per_node in plugin_scores.values():
            for ns in per_node:
                totals[ns.name] += ns.score

        if self.extenders:
            for ext in self.extenders:
                if not ext.is_interested(pod):
                    continue
                try:
                    contributions = ext.prioritize(pod, nodes)
                except Exception:
                    if ext.is_ignorable():
                        continue
                    raise
                for name, score in contributions.items():
                    if name in totals:
                        totals[name] += int(score)

        return [fw.NodeScore(n, totals[n]) for n in node_names]

    @staticmethod
    def select_host(priority_list: List[fw.NodeScore]) -> str:
        """Reservoir-sample among max-score nodes (generic_scheduler.go:154)."""
        if not priority_list:
            raise ValueError("empty priority list")
        max_score = priority_list[0].score
        selected = priority_list[0].name
        count = 1
        for ns in priority_list[1:]:
            if ns.score > max_score:
                max_score, selected, count = ns.score, ns.name, 1
            elif ns.score == max_score:
                count += 1
                if random.randrange(count) == 0:
                    selected = ns.name
        return selected
