"""Scheduler-internal types: Resource vectors, PodInfo, NodeInfo.

Behavioral equivalent of the reference's ``pkg/scheduler/framework/types.go``
(NodeInfo :230-271, Resource :324, PodInfo/AffinityTerm :72-93, QueuedPodInfo
:45, nextGeneration :282, Add/RemovePod :524-633). These structs are the
de-facto feature vectors of the system: per-node aggregates as int64
milli-CPU / bytes plus scalar-resource maps, used-ports sets, image states,
and affinity-specialized pod sublists. The TPU encoder
(``kubernetes_tpu.ops.encode``) flattens exactly these aggregates into dense
device arrays, so keeping them columnar-friendly here is deliberate.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from kubernetes_tpu.api import labels as labelslib
from kubernetes_tpu.api.types import (
    CPU,
    DEFAULT_MILLI_CPU_REQUEST,
    DEFAULT_MEMORY_REQUEST,
    EPHEMERAL_STORAGE,
    MEMORY,
    PODS,
    Node,
    Pod,
    PodAffinityTerm,
)

# Monotonic generation counter shared by all NodeInfos (reference
# types.go:282 nextGeneration / generation package var).
_generation = itertools.count(1)
_generation_lock = threading.Lock()


def next_generation() -> int:
    with _generation_lock:
        return next(_generation)


@dataclass
class Resource:
    """Aggregate resource vector (reference Resource, types.go:324)."""

    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    scalar_resources: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_resource_list(cls, rl: Dict) -> "Resource":
        r = cls()
        for name, q in (rl or {}).items():
            if name == CPU:
                r.milli_cpu = q.milli_value()
            elif name == MEMORY:
                r.memory = q.value()
            elif name == EPHEMERAL_STORAGE:
                r.ephemeral_storage = q.value()
            elif name == PODS:
                r.allowed_pod_number = q.value()
            else:
                # scalar resources (extended, hugepages) count whole units
                r.scalar_resources[name] = q.value()
        return r

    def add(self, other: "Resource") -> None:
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.ephemeral_storage += other.ephemeral_storage
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = self.scalar_resources.get(k, 0) + v

    def sub(self, other: "Resource") -> None:
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.ephemeral_storage -= other.ephemeral_storage
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = self.scalar_resources.get(k, 0) - v

    def set_max(self, other: "Resource") -> None:
        self.milli_cpu = max(self.milli_cpu, other.milli_cpu)
        self.memory = max(self.memory, other.memory)
        self.ephemeral_storage = max(self.ephemeral_storage, other.ephemeral_storage)
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = max(self.scalar_resources.get(k, 0), v)

    def clone(self) -> "Resource":
        return Resource(
            self.milli_cpu,
            self.memory,
            self.ephemeral_storage,
            self.allowed_pod_number,
            dict(self.scalar_resources),
        )


def is_extended(name: str) -> bool:
    return "/" in name


def is_hugepage(name: str) -> bool:
    return name.startswith("hugepages-")


def compute_pod_resource_request(pod: Pod, non_zero: bool = False) -> Resource:
    """max(sum(app containers), max(init containers)) + overhead
    (reference fit.go:148-165 computePodResourceRequest; non_zero variant
    applies the 100m/200Mi defaults from schedutil GetNonzeroRequests)."""
    spec = pod.spec
    if len(spec.containers) == 1 and not spec.init_containers \
            and not spec.overhead:
        # single plain container — the overwhelmingly common shape; skip
        # the aggregate scaffolding (this runs twice per pod on the
        # queue-admission hot path)
        result = _container_request(spec.containers[0], non_zero)
    else:
        result = Resource()
        for c in spec.containers:
            result.add(_container_request(c, non_zero))
        init_max = Resource()
        for c in spec.init_containers:
            init_max.set_max(_container_request(c, non_zero))
        result.set_max(init_max)
        if spec.overhead:
            result.add(Resource.from_resource_list(spec.overhead))
    # a pod request never carries allowed_pod_number (single enforcement
    # site for both paths)
    result.allowed_pod_number = 0
    return result


def _container_request(container, non_zero: bool) -> Resource:
    r = Resource.from_resource_list(container.resources.requests)
    if non_zero:
        if CPU not in container.resources.requests:
            r.milli_cpu = DEFAULT_MILLI_CPU_REQUEST
        if MEMORY not in container.resources.requests:
            r.memory = DEFAULT_MEMORY_REQUEST
    return r


@dataclass(frozen=True)
class AffinityTerm:
    """Pre-parsed (anti-)affinity term (reference types.go:72-82)."""

    namespaces: frozenset
    selector: labelslib.Selector
    topology_key: str

    def matches(self, pod: Pod) -> bool:
        return pod.namespace in self.namespaces and self.selector.matches(
            pod.metadata.labels
        )


@dataclass(frozen=True)
class WeightedAffinityTerm:
    term: AffinityTerm
    weight: int


def _make_term(pod: Pod, term: PodAffinityTerm) -> AffinityTerm:
    namespaces = set(term.namespaces) if term.namespaces else {pod.namespace}
    return AffinityTerm(
        namespaces=frozenset(namespaces),
        selector=labelslib.selector_from_label_selector(term.label_selector),
        topology_key=term.topology_key,
    )


class PodInfo:
    """Pod plus pre-parsed affinity terms (reference types.go:83-93) and the
    precomputed resource requests the hot path reads repeatedly."""

    __slots__ = (
        "pod",
        "required_affinity_terms",
        "required_anti_affinity_terms",
        "preferred_affinity_terms",
        "preferred_anti_affinity_terms",
        "resource_request",
        "non_zero_request",
    )

    @classmethod
    def of(cls, pod: Pod) -> "PodInfo":
        """Memoized constructor: parsing terms and summing resource vectors
        dominates the hot commit path when the same Pod object flows
        through queue → cache → encoder, so cache the PodInfo on the pod.
        The identity check guards against ``copy.copy`` propagating the
        memo to a new pod revision (the copied ``__dict__`` aliases it):
        a hit requires the cached parse to belong to THIS object.

        CONTRACT: Pod objects are immutable once stored — every revision
        is a fresh object (the store's copy-on-write updates, matching
        the reference's serialize-over-the-wire boundary). A caller that
        mutates a stored Pod's labels/containers in place would read a
        stale parse here; don't."""
        pi = pod.__dict__.get("_pod_info")
        if pi is None or pi.pod is not pod:
            pi = cls(pod)
            pod.__dict__["_pod_info"] = pi
        return pi

    @classmethod
    def derived(cls, pod: Pod, base: "PodInfo") -> "PodInfo":
        """A PodInfo for a shallow variant of ``base.pod`` (the assumed
        copy, which differs only in spec.nodeName): share the parsed
        terms and resource vectors instead of re-parsing. The caller
        guarantees containers/affinity/labels are unchanged."""
        pi = cls.__new__(cls)
        pi.pod = pod
        pi.required_affinity_terms = base.required_affinity_terms
        pi.required_anti_affinity_terms = base.required_anti_affinity_terms
        pi.preferred_affinity_terms = base.preferred_affinity_terms
        pi.preferred_anti_affinity_terms = base.preferred_anti_affinity_terms
        pi.resource_request = base.resource_request
        pi.non_zero_request = base.non_zero_request
        pod.__dict__["_pod_info"] = pi
        return pi

    def __init__(self, pod: Pod):
        self.pod = pod
        self.required_affinity_terms: List[AffinityTerm] = []
        self.required_anti_affinity_terms: List[AffinityTerm] = []
        self.preferred_affinity_terms: List[WeightedAffinityTerm] = []
        self.preferred_anti_affinity_terms: List[WeightedAffinityTerm] = []
        aff = pod.spec.affinity
        if aff is not None:
            if aff.pod_affinity is not None:
                for t in aff.pod_affinity.required_during_scheduling_ignored_during_execution:
                    self.required_affinity_terms.append(_make_term(pod, t))
                for wt in aff.pod_affinity.preferred_during_scheduling_ignored_during_execution:
                    self.preferred_affinity_terms.append(
                        WeightedAffinityTerm(_make_term(pod, wt.pod_affinity_term), wt.weight)
                    )
            if aff.pod_anti_affinity is not None:
                for t in aff.pod_anti_affinity.required_during_scheduling_ignored_during_execution:
                    self.required_anti_affinity_terms.append(_make_term(pod, t))
                for wt in aff.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution:
                    self.preferred_anti_affinity_terms.append(
                        WeightedAffinityTerm(_make_term(pod, wt.pod_affinity_term), wt.weight)
                    )
        self.resource_request = compute_pod_resource_request(pod)
        self.non_zero_request = compute_pod_resource_request(pod, non_zero=True)


class QueuedPodInfo:
    """Queue bookkeeping around a PodInfo (reference types.go:45)."""

    __slots__ = ("pod_info", "timestamp", "attempts", "initial_attempt_timestamp")

    def __init__(self, pod: Pod, timestamp: Optional[float] = None, attempts: int = 0):
        now = time.monotonic() if timestamp is None else timestamp
        self.pod_info = PodInfo.of(pod)
        self.timestamp = now
        self.attempts = attempts
        self.initial_attempt_timestamp = now

    @property
    def pod(self) -> Pod:
        return self.pod_info.pod


@dataclass
class ImageStateSummary:
    size: int = 0
    num_nodes: int = 0


# used-ports key: (hostIP, protocol, hostPort) — reference HostPortInfo.
PortKey = Tuple[str, str, int]


def pod_host_ports(pod: Pod) -> List[PortKey]:
    out = []
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port > 0:
                out.append((p.host_ip or "0.0.0.0", p.protocol or "TCP", p.host_port))
    return out


def ports_conflict(used: Set[PortKey], wanted: List[PortKey]) -> bool:
    """HostPortInfo.CheckConflict: 0.0.0.0 conflicts with any IP on the
    same (protocol, port)."""
    if not wanted or not used:
        return False
    for ip, proto, port in wanted:
        for uip, uproto, uport in used:
            if proto == uproto and port == uport:
                if ip == "0.0.0.0" or uip == "0.0.0.0" or ip == uip:
                    return True
    return False


class NodeInfo:
    """Aggregated per-node scheduling state (reference types.go:230-271)."""

    __slots__ = (
        "node",
        "pods",
        "pods_with_affinity",
        "pods_with_required_anti_affinity",
        "used_ports",
        "requested",
        "non_zero_requested",
        "allocatable",
        "image_states",
        "generation",
    )

    def __init__(self, *pods: Pod):
        self.node: Optional[Node] = None
        self.pods: List[PodInfo] = []
        self.pods_with_affinity: List[PodInfo] = []
        self.pods_with_required_anti_affinity: List[PodInfo] = []
        self.used_ports: Set[PortKey] = set()
        self.requested = Resource()
        self.non_zero_requested = Resource()
        self.allocatable = Resource()
        self.image_states: Dict[str, ImageStateSummary] = {}
        self.generation = next_generation()
        for p in pods:
            self.add_pod(p)

    def set_node(self, node: Node) -> None:
        self.node = node
        self.allocatable = Resource.from_resource_list(node.status.allocatable)
        self.generation = next_generation()

    def remove_node(self) -> None:
        self.node = None
        self.generation = next_generation()

    def add_pod(self, pod: Pod) -> None:
        self.add_pod_info(PodInfo.of(pod))

    def add_pod_info(self, pi: PodInfo) -> None:
        self.pods.append(pi)
        if _pod_with_affinity(pi):
            self.pods_with_affinity.append(pi)
        if pi.required_anti_affinity_terms:
            self.pods_with_required_anti_affinity.append(pi)
        self.requested.add(pi.resource_request)
        self.requested.allowed_pod_number = 0  # not meaningful on requested
        self.non_zero_requested.add(pi.non_zero_request)
        self.used_ports.update(pod_host_ports(pi.pod))
        self.generation = next_generation()

    def remove_pod(self, pod: Pod) -> bool:
        for i, pi in enumerate(self.pods):
            if pi.pod.uid == pod.uid:
                self.pods.pop(i)
                self.pods_with_affinity = [
                    x for x in self.pods_with_affinity if x.pod.uid != pod.uid
                ]
                self.pods_with_required_anti_affinity = [
                    x
                    for x in self.pods_with_required_anti_affinity
                    if x.pod.uid != pod.uid
                ]
                self.requested.sub(pi.resource_request)
                self.non_zero_requested.sub(pi.non_zero_request)
                # recompute ports (cheap; pods-per-node is small)
                self.used_ports = set()
                for x in self.pods:
                    self.used_ports.update(pod_host_ports(x.pod))
                self.generation = next_generation()
                return True
        return False

    def clone(self) -> "NodeInfo":
        c = NodeInfo()
        c.copy_from(self)
        return c

    def copy_from(self, other: "NodeInfo") -> None:
        """Overwrite this NodeInfo in place. The snapshot's map and ordered
        lists share NodeInfo identity (like the reference's shared
        pointers, snapshot.go), so incremental updates must mutate the
        existing object rather than replace it."""
        self.node = other.node
        self.pods = list(other.pods)
        self.pods_with_affinity = list(other.pods_with_affinity)
        self.pods_with_required_anti_affinity = list(
            other.pods_with_required_anti_affinity
        )
        self.used_ports = set(other.used_ports)
        self.requested = other.requested.clone()
        self.non_zero_requested = other.non_zero_requested.clone()
        self.allocatable = other.allocatable.clone()
        self.image_states = dict(other.image_states)
        self.generation = other.generation


def _pod_with_affinity(pi: PodInfo) -> bool:
    return bool(
        pi.required_affinity_terms
        or pi.required_anti_affinity_terms
        or pi.preferred_affinity_terms
        or pi.preferred_anti_affinity_terms
    )


def get_pod_key(pod: Pod) -> str:
    return pod.uid
