"""Three-tier scheduling queue + pod nominator.

Behavioral equivalent of the reference's
``pkg/scheduler/internal/queue/scheduling_queue.go``: ``activeQ`` (heap
ordered by the framework's QueueSort less-func), ``podBackoffQ`` (heap by
backoff expiry; exponential 1s→10s), ``unschedulableQ`` (map), the
``schedulingCycle``/``moveRequestCycle`` race-avoidance protocol
(:297-329), event-driven ``move_all_to_active_or_backoff_queue`` (:512-533),
periodic flushes (backoff 1s, unschedulable-leftover 30s period / 60s age),
and an embedded PodNominator for preemption nominations.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.observability import get_tracer
from kubernetes_tpu.scheduler.heap import Heap
from kubernetes_tpu.scheduler.types import PodInfo, QueuedPodInfo, get_pod_key
from kubernetes_tpu.utils.clock import RealClock

DEFAULT_POD_INITIAL_BACKOFF = 1.0   # scheduling_queue.go:57
DEFAULT_POD_MAX_BACKOFF = 10.0      # scheduling_queue.go:59
UNSCHEDULABLE_Q_TIME_INTERVAL = 60.0  # flush age threshold
BACKOFF_FLUSH_INTERVAL = 1.0
UNSCHEDULABLE_FLUSH_INTERVAL = 30.0


class PodNominator:
    """Tracks preemption nominations (reference framework/interface.go:587 +
    queue nominator implementation)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._nominated: Dict[str, List[PodInfo]] = {}  # nodeName -> pods
        self._pod_to_node: Dict[str, str] = {}

    def add_nominated_pod(self, pod: Pod, node_name: str = "") -> None:
        with self._lock:
            self._delete_locked(pod)
            nn = node_name or pod.status.nominated_node_name
            if not nn:
                return
            self._pod_to_node[get_pod_key(pod)] = nn
            self._nominated.setdefault(nn, []).append(PodInfo(pod))

    def delete_nominated_pod_if_exists(self, pod: Pod) -> None:
        with self._lock:
            self._delete_locked(pod)

    def update_nominated_pod(self, old: Pod, new: Pod) -> None:
        with self._lock:
            # preserve the nomination across updates that drop the status field
            nn = self._pod_to_node.get(get_pod_key(old), "")
            self._delete_locked(old)
            self.add_nominated_pod(new, new.status.nominated_node_name or nn)

    def nominated_pods_for_node(self, node_name: str) -> List[PodInfo]:
        with self._lock:
            return list(self._nominated.get(node_name, ()))

    def _delete_locked(self, pod: Pod) -> None:
        key = get_pod_key(pod)
        nn = self._pod_to_node.pop(key, None)
        if nn is not None and nn in self._nominated:
            self._nominated[nn] = [
                pi for pi in self._nominated[nn] if get_pod_key(pi.pod) != key
            ]
            if not self._nominated[nn]:
                del self._nominated[nn]


def default_queue_sort_less(a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
    """PrioritySort less (priority_sort.go:41-45): higher priority first,
    earlier enqueue-timestamp tiebreak."""
    pa, pb = a.pod.priority(), b.pod.priority()
    if pa != pb:
        return pa > pb
    return a.timestamp < b.timestamp


class SchedulingQueue(PodNominator):
    def __init__(
        self,
        less_func: Callable[[QueuedPodInfo, QueuedPodInfo], bool] = default_queue_sort_less,
        clock=None,
        pod_initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
        pod_max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
        metrics=None,
    ):
        super().__init__()
        self._clock = clock or RealClock()
        self._qlock = threading.RLock()
        self._cond = threading.Condition(self._qlock)
        self._initial_backoff = pod_initial_backoff
        self._max_backoff = pod_max_backoff
        self._metrics = metrics

        def key(qpi: QueuedPodInfo) -> str:
            return get_pod_key(qpi.pod)

        self._active_q = Heap(key, less_func)
        # total-order key published by the QueueSort plugin (wired by the
        # configurator when available); enables the bulk C-sorted drain
        self.sort_key: Optional[Callable[[QueuedPodInfo], tuple]] = None
        self._backoff_q = Heap(
            key, lambda a, b: self._backoff_time(a) < self._backoff_time(b),
            sort_key=self._backoff_time,
        )
        self._unschedulable_q: Dict[str, QueuedPodInfo] = {}
        self.scheduling_cycle = 0
        self._move_request_cycle = -1
        self._closed = False
        self._flush_threads: List[threading.Thread] = []
        self._stop = threading.Event()

    # ------------------------------------------------------------------
    def _backoff_time(self, qpi: QueuedPodInfo) -> float:
        return qpi.timestamp + self._backoff_duration(qpi)

    def _backoff_duration(self, qpi: QueuedPodInfo) -> float:
        """initial * 2^attempts, capped (scheduling_queue.go
        calculateBackoffDuration)."""
        d = self._initial_backoff
        for _ in range(1, qpi.attempts):
            d *= 2
            if d >= self._max_backoff:
                return self._max_backoff
        return min(d, self._max_backoff)

    def _backoff_complete(self, qpi: QueuedPodInfo) -> bool:
        return self._clock.now() >= self._backoff_time(qpi)

    # ------------------------------------------------------------------
    def add(self, pod: Pod) -> None:
        with self._cond:
            qpi = self._new_queued_pod_info(pod)
            self._active_q.add(qpi)
            key = get_pod_key(pod)
            self._unschedulable_q.pop(key, None)
            self._backoff_q.delete_by_key(key)
            self.add_nominated_pod(pod)
            if self._metrics:
                self._metrics.pods_added("active", "PodAdd")
            self._cond.notify_all()

    def add_many(self, pods: List[Pod]) -> None:
        """Bulk add under ONE lock + one wakeup (the batched-admission
        delivery path). Per-pod semantics identical to ``add``."""
        if not pods:
            return
        with self._cond:
            for pod in pods:
                qpi = self._new_queued_pod_info(pod)
                self._active_q.add(qpi)
                key = get_pod_key(pod)
                self._unschedulable_q.pop(key, None)
                self._backoff_q.delete_by_key(key)
                self.add_nominated_pod(pod)
            if self._metrics:
                self._metrics.pods_added("active", "PodAdd", amount=len(pods))
            self._cond.notify_all()

    def delete_many(self, pods: List[Pod]) -> None:
        """Bulk delete under one lock (batched bind-transition delivery)."""
        if not pods:
            return
        with self._cond:
            for pod in pods:
                key = get_pod_key(pod)
                self.delete_nominated_pod_if_exists(pod)
                self._active_q.delete_by_key(key)
                self._backoff_q.delete_by_key(key)
                self._unschedulable_q.pop(key, None)

    def assigned_pods_updated(self, pods: List[Pod]) -> None:
        """Bulk affinity-wakeup scan under one lock: same per-pod
        semantics as N assigned_pod_updated calls (each assigned pod is
        matched against the unschedulable pods' affinity terms)."""
        with self._cond:
            if not self._unschedulable_q:
                # the serial path's _move_pods_locked updates the move-
                # request cycle even when nothing moves; the race
                # protocol (scheduling_queue.go:317) depends on it
                self._move_request_cycle = self.scheduling_cycle
                return
            for pod in pods:
                self._move_pods_locked(
                    self._unschedulable_pods_with_matching_affinity(pod),
                    "AssignedPodUpdate",
                )

    def _new_queued_pod_info(self, pod: Pod) -> QueuedPodInfo:
        # carry attempts across queue hops if known
        key = get_pod_key(pod)
        for source in (self._active_q.get_by_key(key), self._backoff_q.get_by_key(key),
                       self._unschedulable_q.get(key)):
            if source is not None:
                source.pod_info = PodInfo(pod)
                source.timestamp = self._clock.now()
                return source
        return QueuedPodInfo(pod, timestamp=self._clock.now())

    def add_unschedulable_if_not_present(
        self, qpi: QueuedPodInfo, pod_scheduling_cycle: int,
        prefer_backoff: bool = False,
    ) -> None:
        """Failed-cycle requeue (scheduling_queue.go:297-329): if a move
        request arrived during this pod's scheduling cycle, the cluster may
        already have changed — send it to backoff instead of unschedulable.
        ``prefer_backoff`` routes the pod to backoff unconditionally: a
        cycle that failed on a SCHEDULER error (transport loss, plugin
        crash) isn't evidence the pod doesn't fit, so it must retry on
        the backoff curve, not park for the unschedulable timeout."""
        with self._cond:
            key = get_pod_key(qpi.pod)
            if (
                self._unschedulable_q.get(key) is not None
                or self._active_q.has_key(key)
                or self._backoff_q.has_key(key)
            ):
                raise ValueError(f"pod {key} already present in a queue")
            qpi.timestamp = self._clock.now()
            if prefer_backoff \
                    or self._move_request_cycle >= pod_scheduling_cycle:
                self._backoff_q.add(qpi)
                if self._metrics:
                    self._metrics.pods_added("backoff", "ScheduleAttemptFailure")
            else:
                self._unschedulable_q[key] = qpi
                if self._metrics:
                    self._metrics.pods_added("unschedulable", "ScheduleAttemptFailure")
            self.add_nominated_pod(qpi.pod)
            self._cond.notify_all()

    def _trace_popped(self, items: List[QueuedPodInfo]) -> None:
        """Record a ``queue.wait`` span (enqueue → pop) for each SAMPLED
        popped pod — the second hop of a pod's causal trace. Runs
        OUTSIDE the queue lock. BOTH endpoints come from the queue
        clock: qpi.timestamp was stamped by it, so the end must be too
        (monotonic under RealClock; under an injected FakeClock mixing
        in time.monotonic() would record hours-long garbage spans)."""
        tracer = get_tracer()
        if not tracer.enabled:
            return
        now = self._clock.now()
        for qpi in items:
            uid = qpi.pod.uid
            if uid and tracer.sampled(uid):
                tracer.record("queue.wait", qpi.timestamp, now, trace=uid,
                              attempts=qpi.attempts)

    def pop(self, timeout: Optional[float] = None) -> Optional[QueuedPodInfo]:
        """Blocks until a pod is available (scheduling_queue.go:379-399)."""
        with self._cond:
            while len(self._active_q) == 0:
                if self._closed:
                    return None
                if not self._cond.wait(timeout):
                    return None
            qpi: QueuedPodInfo = self._active_q.pop()
            qpi.attempts += 1
            self.scheduling_cycle += 1
        self._trace_popped((qpi,))
        return qpi

    def pop_batch(self, max_n: int, timeout: Optional[float] = None,
                  ) -> Tuple[List[QueuedPodInfo], int]:
        """Pop up to ``max_n`` pods in queue order under ONE lock — the
        batch path's drain. When the QueueSort plugin publishes a total-
        order ``sort_key`` (PrioritySort does), the whole active heap is
        drained and C-sorted instead of popping one by one: per-pop heap
        maintenance with a Python less-function costs more than the solve
        for large batches. Each popped pod consumes one scheduling cycle,
        exactly as ``max_n`` serial pops would; returns (pods, cycle of
        the FIRST pop) — computed under the lock so callers need no
        single-consumer assumption to reconstruct per-pod cycles."""
        with self._cond:
            while len(self._active_q) == 0:
                if self._closed:
                    return [], self.scheduling_cycle
                if not self._cond.wait(timeout):
                    return [], self.scheduling_cycle
            n = min(max_n, len(self._active_q))
            if self.sort_key is not None:
                items = self._active_q.pop_all()
                items.sort(key=self.sort_key)
                if len(items) > n:
                    # a sorted list satisfies the heap property: the
                    # remainder goes straight back without sifting
                    self._active_q.replace_all(items[n:])
                    items = items[:n]
            else:
                items = [self._active_q.pop() for _ in range(n)]
            for qpi in items:
                qpi.attempts += 1
            first_cycle = self.scheduling_cycle + 1
            self.scheduling_cycle += len(items)
        self._trace_popped(items)
        return items, first_cycle

    def update(self, old: Optional[Pod], new: Pod) -> None:
        with self._cond:
            key = get_pod_key(new)
            if old is not None:
                for q in (self._active_q, self._backoff_q):
                    existing = q.get_by_key(key)
                    if existing is not None:
                        existing.pod_info = PodInfo(new)
                        q.update(existing)
                        self.update_nominated_pod(old, new)
                        return
            existing = self._unschedulable_q.get(key)
            if existing is not None:
                self.update_nominated_pod(old or existing.pod, new)
                if old is not None and _pod_updated_may_help(old, new):
                    existing.pod_info = PodInfo(new)
                    del self._unschedulable_q[key]
                    if self._backoff_complete(existing):
                        self._active_q.add(existing)
                        self._cond.notify_all()
                    else:
                        self._backoff_q.add(existing)
                else:
                    existing.pod_info = PodInfo(new)
                return
            # not present anywhere: treat as new
            self._active_q.add(self._new_queued_pod_info(new))
            self.add_nominated_pod(new)
            self._cond.notify_all()

    def delete(self, pod: Pod) -> None:
        with self._cond:
            key = get_pod_key(pod)
            self.delete_nominated_pod_if_exists(pod)
            self._active_q.delete_by_key(key)
            self._backoff_q.delete_by_key(key)
            self._unschedulable_q.pop(key, None)

    # ------------------------------------------------------------------
    def move_all_to_active_or_backoff_queue(self, event: str) -> None:
        with self._cond:
            self._move_pods_locked(list(self._unschedulable_q.values()), event)

    def gang_members_added(self, groups) -> None:
        """A new (or re-queued) member of a coscheduling gang ACTIVATES
        its siblings (the out-of-tree plugin's PodGroup activation /
        framework Activate): members parked unschedulable or backing off
        while the gang was short move straight to the active queue —
        bypassing backoff, because a gang completes only when its
        members overlap at Permit, and staggered backoffs prevent the
        overlap forever. ``groups`` is a set of pod-group names (the
        ``pod-group.scheduling.k8s.io/name`` label)."""
        if not groups:
            return
        from kubernetes_tpu.scheduler.framework.plugins.coscheduling import (
            GROUP_NAME_LABEL,
        )

        def in_groups(qpi: QueuedPodInfo) -> bool:
            return qpi.pod.metadata.labels.get(GROUP_NAME_LABEL, "") \
                in groups

        with self._cond:
            moved = False
            for qpi in [q for q in self._unschedulable_q.values()
                        if in_groups(q)]:
                self._unschedulable_q.pop(get_pod_key(qpi.pod), None)
                self._active_q.add(qpi)
                moved = True
            for qpi in [q for q in self._backoff_q.list() if in_groups(q)]:
                self._backoff_q.delete(qpi)
                self._active_q.add(qpi)
                moved = True
            # the moveRequestCycle protocol (scheduling_queue.go:317):
            # a gang member mid-cycle when this wakeup fires must see it,
            # or its failure parks it unschedulable with no further
            # activation events until the permit timeout collapses the gang
            self._move_request_cycle = self.scheduling_cycle
            if moved:
                self._cond.notify_all()

    def assigned_pod_added(self, pod: Pod) -> None:
        with self._cond:
            self._move_pods_locked(
                self._unschedulable_pods_with_matching_affinity(pod),
                "AssignedPodAdd",
            )

    def assigned_pod_updated(self, pod: Pod) -> None:
        with self._cond:
            self._move_pods_locked(
                self._unschedulable_pods_with_matching_affinity(pod),
                "AssignedPodUpdate",
            )

    def _unschedulable_pods_with_matching_affinity(self, pod: Pod) -> List[QueuedPodInfo]:
        """Pods whose (anti-)affinity terms match the newly-assigned pod
        (scheduling_queue.go:483 getUnschedulablePodsWithMatchingAffinityTerm)."""
        if not self._unschedulable_q:
            return []
        out = []
        for qpi in self._unschedulable_q.values():
            pi = qpi.pod_info
            terms = (
                pi.required_affinity_terms
                + pi.required_anti_affinity_terms
                + [wt.term for wt in pi.preferred_affinity_terms]
                + [wt.term for wt in pi.preferred_anti_affinity_terms]
            )
            if any(t.matches(pod) for t in terms):
                out.append(qpi)
        return out

    def _move_pods_locked(self, pods: List[QueuedPodInfo], event: str) -> None:
        for qpi in pods:
            key = get_pod_key(qpi.pod)
            if self._backoff_complete(qpi):
                self._active_q.add(qpi)
            else:
                self._backoff_q.add(qpi)
            self._unschedulable_q.pop(key, None)
            if self._metrics:
                self._metrics.pods_moved(event)
        self._move_request_cycle = self.scheduling_cycle
        self._cond.notify_all()

    # ------------------------------------------------------------------
    # periodic flushes
    def flush_backoff_completed(self) -> None:
        with self._cond:
            moved = False
            while len(self._backoff_q):
                top: QueuedPodInfo = self._backoff_q.peek()
                if not self._backoff_complete(top):
                    break
                self._backoff_q.pop()
                self._active_q.add(top)
                moved = True
            if moved:
                self._cond.notify_all()

    def flush_unschedulable_left_over(self) -> None:
        with self._cond:
            now = self._clock.now()
            stale = [
                qpi
                for qpi in self._unschedulable_q.values()
                if now - qpi.timestamp >= UNSCHEDULABLE_Q_TIME_INTERVAL
            ]
            if stale:
                self._move_pods_locked(stale, "UnschedulableTimeout")

    def run(self) -> None:
        """Start flush threads (scheduling_queue.go:241-244)."""
        for interval, fn in (
            (BACKOFF_FLUSH_INTERVAL, self.flush_backoff_completed),
            (UNSCHEDULABLE_FLUSH_INTERVAL, self.flush_unschedulable_left_over),
        ):
            t = threading.Thread(
                target=self._flush_loop, args=(interval, fn), daemon=True
            )
            t.start()
            self._flush_threads.append(t)

    def _flush_loop(self, interval: float, fn) -> None:
        while not self._stop.wait(interval):
            fn()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._stop.set()
            self._cond.notify_all()

    # introspection (tests + debugger)
    def pending_pods(self) -> List[Pod]:
        with self._qlock:
            return (
                [q.pod for q in self._active_q.list()]
                + [q.pod for q in self._backoff_q.list()]
                + [q.pod for q in self._unschedulable_q.values()]
            )

    def num_active(self) -> int:
        with self._qlock:
            return len(self._active_q)

    def num_backoff(self) -> int:
        with self._qlock:
            return len(self._backoff_q)

    def num_unschedulable(self) -> int:
        with self._qlock:
            return len(self._unschedulable_q)

    def unschedulable_pods(self) -> List[Pod]:
        """The parked unschedulable set — the cluster autoscaler's
        trigger surface (upstream CA watches pods with a FailedScheduling
        condition; here the queue IS that set, exactly: every pod in it
        failed a cycle with an Unschedulable outcome and waits on a
        cluster event)."""
        with self._qlock:
            return [q.pod for q in self._unschedulable_q.values()]

    def pending_active_count(self) -> int:
        """Pods still due a scheduling attempt (active + backoff); pods
        parked in unschedulableQ have been tried and wait on events."""
        with self._qlock:
            return len(self._active_q) + len(self._backoff_q)

    def pending_hint(self) -> Tuple[int, Optional[int]]:
        """Non-blocking drain hint for the streaming scheduler: the
        active-queue size and the priority of the pod the next pop
        would return (the heap root under the QueueSort less-func),
        WITHOUT popping, waiting, or consuming a scheduling cycle.
        The pipelined batch loop reads it while a solve is in flight
        to decide whether a drain for batch N+1 is worth attempting
        at all (and so whether the queue lock is worth taking) —
        stage overlap must never park on an empty queue while a
        commit is pending. The pad bucket itself is sized from the
        drained-and-partitioned batch: the raw hint would overstate
        it whenever serial-fallback pods ride the drain.
        Returns ``(0, None)`` when the active queue is empty. Purely
        advisory: concurrent adds/pops may change the queue before
        the caller acts on it (the hint-vs-pop consistency contract
        is only that a quiet queue reports exactly what pop_batch
        would then drain — tested in tests/test_queue.py)."""
        with self._qlock:
            n = len(self._active_q)
            if n == 0:
                return 0, None
            top: QueuedPodInfo = self._active_q.peek()
            return n, top.pod.priority()


def _pod_updated_may_help(old: Pod, new: Pod) -> bool:
    """Reference isPodUpdated: strip ResourceVersion/Status-y fields and
    compare — we approximate by checking spec/label changes."""
    return (
        old.metadata.labels != new.metadata.labels
        or old.spec.node_selector != new.spec.node_selector
        or old.spec.tolerations != new.spec.tolerations
        or old.spec.priority != new.spec.priority
        or old.spec.affinity != new.spec.affinity
    )
