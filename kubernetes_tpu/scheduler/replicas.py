"""Multi-replica scheduler mode: M brains over one (partitioned) store.

The single-scheduler HA story (``run_with_leader_election``) keeps one
brain active and the rest warm — correct, but the active brain is still
the throughput ceiling. This module runs M brains CONCURRENTLY:

- **pod-hash sharding** (``shard_pods``): every pending pod hashes to
  exactly one replica's queue (crc32 of its uid/name — the same
  cross-process-stable hash family the store partitions use), so no two
  replicas ever race on the same pod. Assigned-pod events still feed
  every replica's cache: each brain sees the capacity its siblings
  consume, just one watch-propagation hop late.
- **node-pool sharding** (``shard_nodes``): optionally, each replica
  also caches a disjoint node pool — conflicts become impossible by
  construction (the measured scale row runs this shape; solving over
  nodes/M also keeps the encoded pod×node planes M× smaller).
- **optimistic conflict resolution on bind** (replicas sharing nodes):
  the commit-time guards arbitrate. Cache half:
  ``commit_capacity_guard`` probes ``SchedulerCache.commit_fits`` at
  commit so a fit a sibling consumed since the solve is refused and
  requeued (``stale_binds_rejected_total{path=capacity}``). Store
  half: the partitioned store's bind-time capacity ledger
  (``CapacityConflictError`` → ``path=bind_conflict``) and the
  same-pod bind CAS (``already assigned`` → ``path=replica_conflict``)
  reject the loser, whose commit unwinds through PR 3's
  unreserve/forget/requeue machinery — two brains cannot double-bind
  a pod or a node.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Callable, List, Optional

from kubernetes_tpu.scheduler.scheduler import Scheduler


def pod_shard_fn(index: int, count: int) -> Callable:
    """Queue-ownership predicate: does this pending pod hash to replica
    ``index``? Keyed by uid when present (stable across requeues), the
    full name otherwise."""

    def owns(pod) -> bool:
        key = pod.uid or f"{pod.namespace}/{pod.metadata.name}"
        return zlib.crc32(key.encode()) % count == index

    return owns


def node_shard_fn(index: int, count: int) -> Callable[[str], bool]:
    """Node-pool predicate: does this node belong to replica
    ``index``'s disjoint pool?"""

    def owns(name: str) -> bool:
        return zlib.crc32(name.encode()) % count == index

    return owns


@dataclass
class ReplicaSpec:
    """How one replica participates in the set. ``shard_pods=False``
    (every replica responsible for every pod) is the conflict-chaos
    configuration: replicas deliberately race, and the bind CAS +
    capacity guards must resolve every collision."""

    index: int
    count: int
    shard_pods: bool = True
    shard_nodes: bool = False
    capacity_guard: bool = True


def install_replica_sharding(sched: Scheduler, spec: ReplicaSpec) -> Scheduler:
    """Wire one scheduler instance into the replica set (idempotent;
    call before ``start()``/``run()`` so the initial replay is already
    filtered)."""
    sched.replica_name = f"replica-{spec.index}"
    if spec.count > 1 and spec.shard_pods:
        sched.pod_shard = pod_shard_fn(spec.index, spec.count)
    if spec.count > 1 and spec.shard_nodes:
        sched.node_shard = node_shard_fn(spec.index, spec.count)
    # the capacity guard matters exactly when replicas share nodes
    sched.commit_capacity_guard = bool(
        spec.capacity_guard and spec.count > 1 and not spec.shard_nodes)
    return sched


class SchedulerReplicaSet:
    """M concurrently-scheduling replicas. ``client_factory(i)`` builds
    each replica's client — over REST every replica needs its OWN
    partition-aware client (its own watch streams and token buckets);
    in-process replicas may all share the store."""

    def __init__(self, client_factory: Callable[[int], object],
                 count: int = 2, shard_pods: bool = True,
                 shard_nodes: bool = False, capacity_guard: bool = True,
                 use_batch: bool = False, max_batch: int = 4096,
                 provider: str = "GangSchedulingProvider",
                 event_client_factory: Optional[Callable] = None):
        from kubernetes_tpu.config.feature_gates import FeatureGates

        self.replicas: List[Scheduler] = []
        self.batch_schedulers: List[object] = []
        for i in range(count):
            sched = Scheduler.create(
                client_factory(i),
                feature_gates=FeatureGates(
                    {"TPUBatchScheduler": use_batch}),
                provider=provider,
                event_client=event_client_factory(i)
                if event_client_factory else None,
            )
            install_replica_sharding(sched, ReplicaSpec(
                index=i, count=count, shard_pods=shard_pods,
                shard_nodes=shard_nodes, capacity_guard=capacity_guard))
            if use_batch:
                from kubernetes_tpu.sidecar import attach_batch_scheduler

                self.batch_schedulers.append(
                    attach_batch_scheduler(sched, max_batch=max_batch))
            self.replicas.append(sched)

    def run(self) -> None:
        for sched in self.replicas:
            sched.run()

    def bound_count(self) -> int:
        """Pods the set has committed (sum of per-replica commit
        metrics — the same series the REST harness counts from)."""
        total = 0
        for sched in self.replicas:
            s = sched.metrics.e2e_scheduling_duration._series.get(
                ("scheduled",))
            total += s[2] if s else 0
        return total

    def flush(self, timeout: float = 30.0) -> None:
        for sched, bs in zip(self.replicas,
                             self.batch_schedulers or
                             [None] * len(self.replicas)):
            if bs is not None:
                bs.flush(timeout=timeout)
            sched.wait_for_inflight_bindings(timeout=timeout)

    def pending_count(self) -> int:
        return sum(s.queue.pending_active_count() for s in self.replicas)

    def stop(self) -> None:
        for sched in self.replicas:
            sched.stop()
