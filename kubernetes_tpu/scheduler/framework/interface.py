"""Scheduling-framework plugin interfaces and status codes.

Behavioral equivalent of the reference's
``pkg/scheduler/framework/interface.go``: the 11 extension points
(QueueSort, PreFilter(+extensions), Filter, PostFilter, PreScore,
Score(+normalize), Reserve, Permit, PreBind, Bind, PostBind), the Status
code lattice (:55-75) — notably the ``Unschedulable`` vs
``UnschedulableAndUnresolvable`` distinction preemption relies on — and the
score bounds (MaxNodeScore=100, :95).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.types import NodeInfo, QueuedPodInfo

# Status codes (interface.go:55-75)
SUCCESS = 0
ERROR = 1
UNSCHEDULABLE = 2
UNSCHEDULABLE_AND_UNRESOLVABLE = 3
WAIT = 4
SKIP = 5

_CODE_NAMES = {
    SUCCESS: "Success",
    ERROR: "Error",
    UNSCHEDULABLE: "Unschedulable",
    UNSCHEDULABLE_AND_UNRESOLVABLE: "UnschedulableAndUnresolvable",
    WAIT: "Wait",
    SKIP: "Skip",
}

MAX_NODE_SCORE = 100  # interface.go:95
MIN_NODE_SCORE = 0
MAX_TOTAL_SCORE = (1 << 63) - 1


class Status:
    """Plugin result. ``None`` is treated as Success everywhere, matching
    the reference's nil-*Status convention."""

    __slots__ = ("code", "reasons", "failed_plugin")

    def __init__(self, code: int = SUCCESS, *reasons: str, failed_plugin: str = ""):
        self.code = code
        self.reasons = list(reasons)
        self.failed_plugin = failed_plugin

    @staticmethod
    def success() -> Optional["Status"]:
        return None

    @staticmethod
    def is_ok(s: Optional["Status"]) -> bool:
        return s is None or s.code == SUCCESS

    def is_success(self) -> bool:
        return self.code == SUCCESS

    def is_unschedulable(self) -> bool:
        return self.code in (UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE)

    def code_name(self) -> str:
        return _CODE_NAMES.get(self.code, str(self.code))

    def message(self) -> str:
        return ", ".join(self.reasons)

    def with_failed_plugin(self, name: str) -> "Status":
        self.failed_plugin = name
        return self

    def as_error(self) -> Exception:
        return RuntimeError(self.message() or self.code_name())

    def __repr__(self):
        return f"Status({self.code_name()}, {self.reasons!r})"

    def __eq__(self, other):
        if other is None:
            return self.code == SUCCESS
        return (
            isinstance(other, Status)
            and self.code == other.code
            and self.reasons == other.reasons
        )


NodeToStatusMap = Dict[str, Status]


@dataclass
class NodeScore:
    name: str
    score: int


@dataclass
class PostFilterResult:
    nominated_node_name: str = ""


@dataclass
class FitError(Exception):
    """Raised when no node fits (reference core.FitError): carries the
    per-node filter statuses preemption and diagnostics read.

    ``message`` (when set) short-circuits ``__str__`` — the batch
    mass-decline path shares one statuses map across thousands of pods,
    and aggregating it per pod is O(nodes) each."""

    pod: Pod = None
    num_all_nodes: int = 0
    filtered_nodes_statuses: NodeToStatusMap = field(default_factory=dict)
    message: str = ""

    def __str__(self):
        if self.message:
            return self.message
        reasons: Dict[str, int] = {}
        for s in self.filtered_nodes_statuses.values():
            for r in s.reasons:
                reasons[r] = reasons.get(r, 0) + 1
        parts = [f"{n} {m}" for m, n in sorted(reasons.items(), key=lambda kv: kv[0])]
        self.message = (
            f"0/{self.num_all_nodes} nodes are available: {', '.join(parts)}."
            if parts
            else f"0/{self.num_all_nodes} nodes are available."
        )
        return self.message


class Plugin:
    """Base plugin; subclasses override the extension points they implement.
    ``NAME`` mirrors the reference's Name() identity used in config."""

    NAME = "Plugin"

    def name(self) -> str:
        return self.NAME


class QueueSortPlugin(Plugin):
    def less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        raise NotImplementedError


class PreFilterExtensions:
    """Incremental PreFilter-state updates used when evaluating nominated
    pods and preemption victims (interface.go PreFilterExtensions)."""

    def add_pod(self, state, pod_to_schedule: Pod, pod_to_add: Pod,
                node_info: NodeInfo) -> Optional[Status]:
        return None

    def remove_pod(self, state, pod_to_schedule: Pod, pod_to_remove: Pod,
                   node_info: NodeInfo) -> Optional[Status]:
        return None


class PreFilterPlugin(Plugin):
    def pre_filter(self, state, pod: Pod) -> Optional[Status]:
        raise NotImplementedError

    def pre_filter_extensions(self) -> Optional[PreFilterExtensions]:
        return None


class FilterPlugin(Plugin):
    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        raise NotImplementedError


class PostFilterPlugin(Plugin):
    def post_filter(self, state, pod: Pod,
                    filtered_node_status_map: NodeToStatusMap):
        """returns (PostFilterResult | None, Status)"""
        raise NotImplementedError


class PreScorePlugin(Plugin):
    def pre_score(self, state, pod: Pod, nodes: List) -> Optional[Status]:
        raise NotImplementedError


class ScoreExtensions:
    def normalize_score(self, state, pod: Pod,
                        scores: List[NodeScore]) -> Optional[Status]:
        return None


class ScorePlugin(Plugin):
    def score(self, state, pod: Pod, node_name: str):
        """returns (int score, Status)"""
        raise NotImplementedError

    def score_extensions(self) -> Optional[ScoreExtensions]:
        return None


class ReservePlugin(Plugin):
    def reserve(self, state, pod: Pod, node_name: str) -> Optional[Status]:
        return None

    def unreserve(self, state, pod: Pod, node_name: str) -> None:
        return None


class PermitPlugin(Plugin):
    def permit(self, state, pod: Pod, node_name: str):
        """returns (Status, timeout_seconds). Status Wait parks the pod in
        the waiting-pods map until Allow/Reject or timeout."""
        raise NotImplementedError


class PreBindPlugin(Plugin):
    def pre_bind(self, state, pod: Pod, node_name: str) -> Optional[Status]:
        raise NotImplementedError


class BindPlugin(Plugin):
    def bind(self, state, pod: Pod, node_name: str) -> Optional[Status]:
        """Skip status delegates to the next bind plugin."""
        raise NotImplementedError


class PostBindPlugin(Plugin):
    def post_bind(self, state, pod: Pod, node_name: str) -> None:
        raise NotImplementedError
