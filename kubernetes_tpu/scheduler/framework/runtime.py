"""Framework runtime: plugin registry, the per-profile framework instance
that executes each extension point, and the Permit waiting-pods map.

Behavioral equivalent of the reference's
``pkg/scheduler/framework/runtime/framework.go`` (frameworkImpl :67-96,
NewFramework :238-355, RunScorePlugins' three passes :721-790,
RunFilterPluginsWithNominatedPods' run-twice protocol :610-684,
RunPermitPlugins/WaitOnPermit :960-1040) and ``waiting_pods_map.go``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.config.types import KubeSchedulerProfile, Plugins
from kubernetes_tpu.scheduler.framework.cycle_state import CycleState
from kubernetes_tpu.scheduler.framework import interface as fw
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, QueuedPodInfo
from kubernetes_tpu.utils.parallelize import Parallelizer

MAX_TIMEOUT = 15 * 60.0  # max permit wait (framework.go:47)


class Registry(dict):
    """name -> factory(args: dict, handle) -> Plugin (runtime/registry.go)."""

    def register(self, name: str, factory) -> None:
        if name in self:
            raise ValueError(f"plugin {name} already registered")
        self[name] = factory

    def merge(self, other: "Registry") -> None:
        for name, factory in other.items():
            self.register(name, factory)


class WaitingPod:
    """A pod parked at Permit (waiting_pods_map.go:30)."""

    def __init__(self, pod: Pod, plugin_timeouts: Dict[str, float]):
        self.pod = pod
        self._lock = threading.Lock()
        self._pending = set(plugin_timeouts)
        self._event = threading.Event()
        self._status: Optional[fw.Status] = None
        # the pod is rejected when the EARLIEST plugin timeout expires
        # (waiting_pods_map.go: per-plugin timers, first to fire rejects)
        self._deadline = time.monotonic() + (
            min(plugin_timeouts.values()) if plugin_timeouts else 0.0
        )

    def pending_plugins(self) -> List[str]:
        with self._lock:
            return sorted(self._pending)

    def allow(self, plugin_name: str) -> None:
        with self._lock:
            self._pending.discard(plugin_name)
            if self._pending and self._status is None:
                return
            if self._status is None:
                self._status = fw.Status(fw.SUCCESS)
        self._event.set()

    def reject(self, plugin_name: str, msg: str = "") -> None:
        with self._lock:
            if self._status is None:
                self._status = fw.Status(
                    fw.UNSCHEDULABLE, msg or f"rejected by {plugin_name}",
                    failed_plugin=plugin_name,
                )
        self._event.set()

    def wait(self) -> fw.Status:
        remaining = self._deadline - time.monotonic()
        if not self._event.wait(timeout=max(0.0, remaining)):
            return fw.Status(
                fw.UNSCHEDULABLE,
                f"pod {self.pod.full_name()} rejected: timed out waiting at Permit",
            )
        with self._lock:
            return self._status or fw.Status(fw.SUCCESS)


class Framework:
    """One instance per scheduler profile. The framework itself is the
    plugin Handle (the reference's frameworkImpl implements
    framework.Handle): it delegates cluster-state access to a ``deps``
    object providing ``snapshot()``, ``client``, ``pod_nominator``,
    ``feature_gates``, and ``parallelizer``."""

    def __init__(
        self,
        registry: Registry,
        profile: KubeSchedulerProfile,
        default_plugins: Plugins,
        deps=None,
        metrics=None,
    ):
        self.profile_name = profile.scheduler_name
        self.deps = deps
        self.handle = self  # plugins receive the framework as their handle
        self.metrics = metrics
        plugins = (
            profile.plugins.merge_defaults(default_plugins)
            if profile.plugins is not None
            else default_plugins
        )
        self._plugins = plugins

        # instantiate each referenced plugin exactly once
        instances: Dict[str, fw.Plugin] = {}
        for point in (
            "queue_sort", "pre_filter", "filter", "post_filter", "pre_score",
            "score", "reserve", "permit", "pre_bind", "bind", "post_bind",
        ):
            for entry in plugins.get(point).enabled:
                if entry.name in instances:
                    continue
                factory = registry.get(entry.name)
                if factory is None:
                    raise ValueError(f"plugin {entry.name!r} not in registry")
                instances[entry.name] = factory(
                    profile.get_plugin_args(entry.name), self
                )
        self._instances = instances

        def plugin_list(point: str) -> List[fw.Plugin]:
            return [instances[e.name] for e in plugins.get(point).enabled]

        self.queue_sort_plugins: List[fw.QueueSortPlugin] = plugin_list("queue_sort")
        self.pre_filter_plugins: List[fw.PreFilterPlugin] = plugin_list("pre_filter")
        self.filter_plugins: List[fw.FilterPlugin] = plugin_list("filter")
        self.post_filter_plugins: List[fw.PostFilterPlugin] = plugin_list("post_filter")
        self.pre_score_plugins: List[fw.PreScorePlugin] = plugin_list("pre_score")
        self.score_plugins: List[fw.ScorePlugin] = plugin_list("score")
        self.reserve_plugins: List[fw.ReservePlugin] = plugin_list("reserve")
        self.permit_plugins: List[fw.PermitPlugin] = plugin_list("permit")
        self.pre_bind_plugins: List[fw.PreBindPlugin] = plugin_list("pre_bind")
        self.bind_plugins: List[fw.BindPlugin] = plugin_list("bind")
        self.post_bind_plugins: List[fw.PostBindPlugin] = plugin_list("post_bind")

        self.score_plugin_weight = {
            e.name: e.weight for e in plugins.get("score").enabled
        }
        for name, w in self.score_plugin_weight.items():
            if w <= 0:
                raise ValueError(f"score plugin {name} has non-positive weight")

        self._waiting_pods: Dict[str, WaitingPod] = {}
        self._waiting_lock = threading.Lock()
        self.parallelizer: Parallelizer = getattr(
            deps, "parallelizer", None
        ) or Parallelizer()

    # ------------------------------------------------------------------
    # Handle surface (delegated to deps)
    def snapshot(self):
        return self.deps.snapshot()

    @property
    def client(self):
        return self.deps.client

    @property
    def pod_nominator(self):
        return getattr(self.deps, "pod_nominator", None)

    @property
    def feature_gates(self):
        return getattr(self.deps, "feature_gates", None)

    @property
    def event_recorder(self):
        """The profile's EventRecorder (reference Handle.EventRecorder);
        None when the deps bundle doesn't provide one (unit tests)."""
        return getattr(self.deps, "event_recorder", None)

    @property
    def extenders(self):
        return getattr(self.deps, "extenders", ())

    # ------------------------------------------------------------------
    def get_plugin(self, name: str) -> Optional[fw.Plugin]:
        return self._instances.get(name)

    def has_filter_plugins(self) -> bool:
        return bool(self.filter_plugins)

    def has_score_plugins(self) -> bool:
        return bool(self.score_plugins)

    def has_post_filter_plugins(self) -> bool:
        return bool(self.post_filter_plugins)

    def list_plugins(self) -> Dict[str, List[str]]:
        return {
            point: [e.name for e in self._plugins.get(point).enabled]
            for point in (
                "queue_sort", "pre_filter", "filter", "post_filter", "pre_score",
                "score", "reserve", "permit", "pre_bind", "bind", "post_bind",
            )
        }

    def _record(self, extension_point: str, status: Optional[fw.Status],
                start: float) -> None:
        if self.metrics is not None:
            self.metrics.observe_extension_point(
                extension_point,
                "Success" if fw.Status.is_ok(status) else status.code_name(),
                time.monotonic() - start,
                profile=self.profile_name,
            )

    # ------------------------------------------------------------------
    def queue_sort_less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        return self.queue_sort_plugins[0].less(a, b)

    @property
    def queue_sort_key(self):
        """The QueueSort plugin's total-order key fn, or None when the
        plugin only defines a comparator."""
        return getattr(self.queue_sort_plugins[0], "sort_key", None)

    def run_pre_filter_plugins(self, state: CycleState, pod: Pod) -> Optional[fw.Status]:
        start = time.monotonic()
        for p in self.pre_filter_plugins:
            status = p.pre_filter(state, pod)
            if not fw.Status.is_ok(status):
                status.with_failed_plugin(p.name())
                if status.is_unschedulable():
                    self._record("PreFilter", status, start)
                    return status
                self._record("PreFilter", status, start)
                return fw.Status(
                    fw.ERROR,
                    f"running PreFilter plugin {p.name()}: {status.message()}",
                    failed_plugin=p.name(),
                )
        self._record("PreFilter", None, start)
        return None

    def run_pre_filter_extension_add_pod(
        self, state: CycleState, pod: Pod, pod_to_add: Pod, node_info: NodeInfo
    ) -> Optional[fw.Status]:
        for p in self.pre_filter_plugins:
            ext = p.pre_filter_extensions()
            if ext is not None:
                status = ext.add_pod(state, pod, pod_to_add, node_info)
                if not fw.Status.is_ok(status):
                    return status
        return None

    def run_pre_filter_extension_remove_pod(
        self, state: CycleState, pod: Pod, pod_to_remove: Pod, node_info: NodeInfo
    ) -> Optional[fw.Status]:
        for p in self.pre_filter_plugins:
            ext = p.pre_filter_extensions()
            if ext is not None:
                status = ext.remove_pod(state, pod, pod_to_remove, node_info)
                if not fw.Status.is_ok(status):
                    return status
        return None

    def run_filter_plugins(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[fw.Status]:
        for p in self.filter_plugins:
            status = p.filter(state, pod, node_info)
            if not fw.Status.is_ok(status):
                if not status.is_unschedulable():
                    status = fw.Status(
                        fw.ERROR,
                        f"running {p.name()} filter plugin: {status.message()}",
                    )
                status.with_failed_plugin(p.name())
                return status
        return None

    def run_filter_plugins_with_nominated_pods(
        self, state: CycleState, pod: Pod, node_info: NodeInfo
    ) -> Optional[fw.Status]:
        """Run filters up to twice (framework.go:610-684): once with
        higher-priority nominated pods added to the node, and — if that
        passed and nominated pods existed — once without, because
        anti-affinity-style filters can pass only when the nominated pods
        are absent."""
        nominator = getattr(self.handle, "pod_nominator", None)
        for attempt in range(2):
            state_to_use, info_to_use = state, node_info
            if attempt == 0:
                added, state_to_use, info_to_use = self._add_nominated_pods(
                    state, pod, node_info, nominator
                )
                if not added:
                    # no nominated pods: single pass suffices
                    return self.run_filter_plugins(state, pod, node_info)
            status = self.run_filter_plugins(state_to_use, pod, info_to_use)
            if not fw.Status.is_ok(status):
                return status
        return None

    def _add_nominated_pods(
        self, state: CycleState, pod: Pod, node_info: NodeInfo, nominator
    ) -> Tuple[bool, CycleState, NodeInfo]:
        if nominator is None or node_info.node is None:
            return False, state, node_info
        nominated = nominator.nominated_pods_for_node(node_info.node.name)
        relevant = [
            pi for pi in nominated
            if pi.pod.uid != pod.uid and pi.pod.priority() >= pod.priority()
        ]
        if not relevant:
            return False, state, node_info
        node_out = node_info.clone()
        state_out = state.clone()
        for pi in relevant:
            node_out.add_pod_info(pi)
            self.run_pre_filter_extension_add_pod(state_out, pod, pi.pod, node_out)
        return True, state_out, node_out

    def run_post_filter_plugins(
        self, state: CycleState, pod: Pod, statuses: fw.NodeToStatusMap
    ) -> Tuple[Optional[fw.PostFilterResult], fw.Status]:
        start = time.monotonic()
        final = fw.Status(fw.UNSCHEDULABLE, "no candidates")
        for p in self.post_filter_plugins:
            result, status = p.post_filter(state, pod, statuses)
            if fw.Status.is_ok(status):
                self._record("PostFilter", status, start)
                return result, status or fw.Status(fw.SUCCESS)
            if not (status and status.is_unschedulable()):
                self._record("PostFilter", status, start)
                return None, fw.Status(
                    fw.ERROR, f"running PostFilter plugin {p.name()}: {status.message()}"
                )
            final = status
        self._record("PostFilter", final, start)
        return None, final

    def run_pre_score_plugins(
        self, state: CycleState, pod: Pod, nodes: List
    ) -> Optional[fw.Status]:
        start = time.monotonic()
        for p in self.pre_score_plugins:
            status = p.pre_score(state, pod, nodes)
            if not fw.Status.is_ok(status):
                self._record("PreScore", status, start)
                return fw.Status(
                    fw.ERROR, f"running PreScore plugin {p.name()}: {status.message()}"
                )
        self._record("PreScore", None, start)
        return None

    def run_score_plugins(
        self, state: CycleState, pod: Pod, node_names: List[str]
    ) -> Tuple[Dict[str, List[fw.NodeScore]], Optional[fw.Status]]:
        """Three passes (framework.go:734,754,772): score per node (parallel
        over nodes), normalize per plugin, apply weights — returning
        plugin -> [NodeScore] like the reference PluginToNodeScores."""
        start = time.monotonic()
        scores: Dict[str, List[fw.NodeScore]] = {
            p.name(): [fw.NodeScore(n, 0) for n in node_names]
            for p in self.score_plugins
        }
        errs: List[str] = []

        def score_node(i: int) -> None:
            for p in self.score_plugins:
                s, status = p.score(state, pod, node_names[i])
                if not fw.Status.is_ok(status):
                    errs.append(f"{p.name()}: {status.message()}")
                    return
                scores[p.name()][i] = fw.NodeScore(node_names[i], s)

        self.parallelizer.until(len(node_names), score_node)
        if errs:
            return scores, fw.Status(fw.ERROR, *errs)

        for p in self.score_plugins:
            ext = p.score_extensions()
            if ext is not None:
                status = ext.normalize_score(state, pod, scores[p.name()])
                if not fw.Status.is_ok(status):
                    return scores, fw.Status(
                        fw.ERROR, f"normalizing {p.name()}: {status.message()}"
                    )

        for p in self.score_plugins:
            weight = self.score_plugin_weight[p.name()]
            for ns in scores[p.name()]:
                if not (fw.MIN_NODE_SCORE <= ns.score <= fw.MAX_NODE_SCORE):
                    return scores, fw.Status(
                        fw.ERROR,
                        f"plugin {p.name()} returns an invalid score {ns.score}",
                    )
                ns.score *= weight
        self._record("Score", None, start)
        return scores, None

    def run_reserve_plugins_reserve(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Optional[fw.Status]:
        start = time.monotonic()
        for i, p in enumerate(self.reserve_plugins):
            status = p.reserve(state, pod, node_name)
            if not fw.Status.is_ok(status):
                # roll back successful reservations in reverse order
                for q in reversed(self.reserve_plugins[:i]):
                    q.unreserve(state, pod, node_name)
                self._record("Reserve", status, start)
                return fw.Status(
                    fw.ERROR, f"running Reserve plugin {p.name()}: {status.message()}"
                )
        self._record("Reserve", None, start)
        return None

    def run_reserve_plugins_unreserve(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> None:
        for p in reversed(self.reserve_plugins):
            p.unreserve(state, pod, node_name)

    def run_permit_plugins(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Optional[fw.Status]:
        start = time.monotonic()
        plugin_timeouts: Dict[str, float] = {}
        status_code = fw.SUCCESS
        for p in self.permit_plugins:
            status, timeout = p.permit(state, pod, node_name)
            if not fw.Status.is_ok(status):
                if status.is_unschedulable():
                    self._record("Permit", status, start)
                    return status.with_failed_plugin(p.name())
                if status.code == fw.WAIT:
                    plugin_timeouts[p.name()] = min(
                        timeout if timeout and timeout > 0 else MAX_TIMEOUT,
                        MAX_TIMEOUT,
                    )
                    status_code = fw.WAIT
                else:
                    self._record("Permit", status, start)
                    return fw.Status(
                        fw.ERROR,
                        f"running Permit plugin {p.name()}: {status.message()}",
                    )
        if status_code == fw.WAIT:
            wp = WaitingPod(pod, plugin_timeouts)
            with self._waiting_lock:
                self._waiting_pods[pod.uid] = wp
            self._record("Permit", None, start)
            return fw.Status(fw.WAIT, f"pod waiting at permit: {sorted(plugin_timeouts)}")
        self._record("Permit", None, start)
        return None

    def wait_on_permit(self, pod: Pod) -> Optional[fw.Status]:
        with self._waiting_lock:
            wp = self._waiting_pods.get(pod.uid)
        if wp is None:
            return None
        try:
            status = wp.wait()
        finally:
            with self._waiting_lock:
                self._waiting_pods.pop(pod.uid, None)
        if not status.is_success():
            return status
        return None

    def get_waiting_pod(self, uid: str) -> Optional[WaitingPod]:
        with self._waiting_lock:
            return self._waiting_pods.get(uid)

    def iterate_waiting_pods(self, fn: Callable[[WaitingPod], None]) -> None:
        with self._waiting_lock:
            pods = list(self._waiting_pods.values())
        for wp in pods:
            fn(wp)

    def reject_waiting_pod(self, uid: str) -> bool:
        wp = self.get_waiting_pod(uid)
        if wp is None:
            return False
        wp.reject("", "removed from waiting")
        return True

    def run_pre_bind_plugins(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Optional[fw.Status]:
        start = time.monotonic()
        for p in self.pre_bind_plugins:
            status = p.pre_bind(state, pod, node_name)
            if not fw.Status.is_ok(status):
                self._record("PreBind", status, start)
                return fw.Status(
                    fw.ERROR, f"running PreBind plugin {p.name()}: {status.message()}"
                )
        self._record("PreBind", None, start)
        return None

    def run_bind_plugins(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Optional[fw.Status]:
        start = time.monotonic()
        if not self.bind_plugins:
            return fw.Status(fw.ERROR, "no bind plugin configured")
        for p in self.bind_plugins:
            status = p.bind(state, pod, node_name)
            if status is not None and status.code == fw.SKIP:
                continue
            if not fw.Status.is_ok(status):
                self._record("Bind", status, start)
                return fw.Status(
                    fw.ERROR, f"running Bind plugin {p.name()}: {status.message()}"
                )
            self._record("Bind", status, start)
            return status
        self._record("Bind", None, start)
        return fw.Status(fw.ERROR, "all bind plugins skipped")

    def run_bind_plugins_bulk(
        self, states: List[CycleState], pods: List[Pod],
        node_names: List[str],
    ) -> List[Optional[fw.Status]]:
        """Bind a whole batch. When the single configured bind plugin
        supports bulk binding (DefaultBinder does: one store lock + one
        batched watch delivery for N bindings), delegate once; otherwise
        fall back to N ``run_bind_plugins`` calls. Per-pod statuses are
        returned positionally — each pod's bind is still its own
        transaction, exactly as in the serial path."""
        start = time.monotonic()
        if len(self.bind_plugins) == 1 and hasattr(
            self.bind_plugins[0], "bind_many"
        ):
            statuses = self.bind_plugins[0].bind_many(states, pods, node_names)
            self._record("Bind", None, start)
            return [
                s if fw.Status.is_ok(s) else fw.Status(
                    fw.ERROR,
                    f"running Bind plugin {self.bind_plugins[0].name()}: "
                    f"{s.message()}",
                )
                for s in statuses
            ]
        return [
            self.run_bind_plugins(state, pod, node)
            for state, pod, node in zip(states, pods, node_names)
        ]

    def run_post_bind_plugins(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> None:
        for p in self.post_bind_plugins:
            p.post_bind(state, pod, node_name)
