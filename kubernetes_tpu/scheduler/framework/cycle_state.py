"""Per-scheduling-cycle key-value state (reference
``framework/cycle_state.go:36-``): the PreFilter→Filter data handoff, with
Clone support for preemption dry-runs and a flag that samples per-plugin
metrics on ~10% of cycles (scheduler.go:56,450)."""

from __future__ import annotations

import copy
from typing import Any, Dict


class CycleState:
    __slots__ = ("_storage", "record_plugin_metrics")

    def __init__(self):
        self._storage: Dict[str, Any] = {}
        self.record_plugin_metrics = False

    def read(self, key: str) -> Any:
        if key not in self._storage:
            raise KeyError(f"{key} not found in CycleState")
        return self._storage[key]

    def write(self, key: str, value: Any) -> None:
        self._storage[key] = value

    def delete(self, key: str) -> None:
        self._storage.pop(key, None)

    def clone(self) -> "CycleState":
        c = CycleState()
        c.record_plugin_metrics = self.record_plugin_metrics
        for k, v in self._storage.items():
            clone_fn = getattr(v, "clone", None)
            c._storage[k] = clone_fn() if callable(clone_fn) else copy.copy(v)
        return c
