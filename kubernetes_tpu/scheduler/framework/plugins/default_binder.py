"""DefaultBinder (reference ``plugins/defaultbinder/default_binder.go:50-61``):
issues the Binding — the equivalent of POST pods/{name}/binding — through
the client."""

from typing import Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import BindPlugin, Status


class DefaultBinder(BindPlugin):
    NAME = "DefaultBinder"

    @staticmethod
    def factory(args, handle):
        return DefaultBinder(handle)

    def __init__(self, handle=None):
        self.handle = handle

    def bind(self, state, pod: Pod, node_name: str) -> Optional[Status]:
        try:
            self.handle.client.bind(pod.namespace, pod.name, pod.uid, node_name)
        except Exception as e:  # surface as Error status like the reference
            return Status(1, str(e))
        return None

    def bind_many(self, states, pods, node_names) -> list:
        """Bulk Binding for the batch commit path: one ``bind_many`` call
        to the store (one lock + one batched watch delivery) instead of
        N round-trips. Each binding remains its own transaction; per-pod
        failures come back positionally as Error statuses."""
        try:
            errors = self.handle.client.bind_many([
                (p.namespace, p.name, p.uid, node)
                for p, node in zip(pods, node_names)
            ])
        except Exception as e:  # noqa: BLE001 — batch-level failure (e.g.
            # a watcher raising during the synchronous dispatch) must
            # surface as per-pod Error statuses, like serial bind's
            # try/except, so the caller unwinds instead of stranding
            # assumed pods
            return [Status(1, str(e))] * len(pods)
        return [None if e is None else Status(1, str(e)) for e in errors]
