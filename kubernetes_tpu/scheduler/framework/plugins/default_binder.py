"""DefaultBinder (reference ``plugins/defaultbinder/default_binder.go:50-61``):
issues the Binding — the equivalent of POST pods/{name}/binding — through
the client."""

from typing import Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import BindPlugin, Status


class DefaultBinder(BindPlugin):
    NAME = "DefaultBinder"

    @staticmethod
    def factory(args, handle):
        return DefaultBinder(handle)

    def __init__(self, handle=None):
        self.handle = handle

    def bind(self, state, pod: Pod, node_name: str) -> Optional[Status]:
        try:
            self.handle.client.bind(pod.namespace, pod.name, pod.uid, node_name)
        except Exception as e:  # surface as Error status like the reference
            return Status(1, str(e))
        return None
