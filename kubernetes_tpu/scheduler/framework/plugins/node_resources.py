"""NodeResources plugin family (reference ``plugins/noderesources/``):

- ``Fit`` — PreFilter+Filter feasibility: pod request vector
  (max(sum(containers), init) + overhead, fit.go:148-165) vs
  ``allocatable − requested`` per resource, plus the pod-count cap
  (fit.go:230-302).
- ``BalancedAllocation`` — ``(1 − |cpuFrac − memFrac|)·100``
  (balanced_allocation.go:82-112).
- ``LeastAllocated`` / ``MostAllocated`` — free/used capacity fraction
  averaged over cpu+mem.
- ``RequestedToCapacityRatio`` — user-shaped piecewise-linear scoring.

Scoring uses non-zero requests (100m/200Mi floors) like the reference's
``resource_allocation.go`` scaffold; Fit uses actual requests.
"""

from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    MAX_NODE_SCORE,
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    PreFilterPlugin,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import (
    NodeInfo,
    Resource,
    compute_pod_resource_request,
)

PRE_FILTER_STATE_KEY = "PreFilterNodeResourcesFit"


class Fit(PreFilterPlugin, FilterPlugin):
    NAME = "NodeResourcesFit"

    @staticmethod
    def factory(args, handle):
        return Fit(args or {})

    def __init__(self, args=None):
        args = args or {}
        self.ignored_resources = set(args.get("ignoredResources") or [])
        self.ignored_resource_groups = set(args.get("ignoredResourceGroups") or [])

    def pre_filter(self, state, pod: Pod) -> Optional[Status]:
        state.write(PRE_FILTER_STATE_KEY, compute_pod_resource_request(pod))
        return None

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        try:
            request: Resource = state.read(PRE_FILTER_STATE_KEY)
        except KeyError:
            request = compute_pod_resource_request(pod)
        reasons = fits_request(
            request, node_info, self.ignored_resources, self.ignored_resource_groups
        )
        if reasons:
            return Status(UNSCHEDULABLE, *reasons)
        return None


def fits_request(
    request: Resource,
    node_info: NodeInfo,
    ignored_resources=frozenset(),
    ignored_groups=frozenset(),
) -> List[str]:
    """Returns insufficient-resource reasons (fit.go:230-302)."""
    reasons: List[str] = []
    allowed = node_info.allocatable.allowed_pod_number
    if len(node_info.pods) + 1 > allowed > 0:
        reasons.append("Too many pods")
    if (
        request.milli_cpu == 0
        and request.memory == 0
        and request.ephemeral_storage == 0
        and not request.scalar_resources
    ):
        return reasons
    alloc, used = node_info.allocatable, node_info.requested
    if request.milli_cpu > alloc.milli_cpu - used.milli_cpu:
        reasons.append("Insufficient cpu")
    if request.memory > alloc.memory - used.memory:
        reasons.append("Insufficient memory")
    if request.ephemeral_storage > alloc.ephemeral_storage - used.ephemeral_storage:
        reasons.append("Insufficient ephemeral-storage")
    for name, quantity in request.scalar_resources.items():
        if name in ignored_resources:
            continue
        if "/" in name and name.split("/", 1)[0] in ignored_groups:
            continue
        if quantity > alloc.scalar_resources.get(name, 0) - used.scalar_resources.get(
            name, 0
        ):
            reasons.append(f"Insufficient {name}")
    return reasons


class _ResourceAllocationScorer(ScorePlugin):
    """Shared scaffold (resource_allocation.go): assemble per-resource
    (requested-including-this-pod, allocatable) pairs using non-zero
    requests, then delegate to a shaping function."""

    resources: Dict[str, int] = {"cpu": 1, "memory": 1}

    def __init__(self, handle=None):
        self.handle = handle

    def score(self, state, pod: Pod, node_name: str) -> Tuple[int, Optional[Status]]:
        node_info = self.handle.snapshot().get(node_name)
        if node_info is None or node_info.node is None:
            return 0, Status(1, f"node {node_name} not found")
        pod_request = compute_pod_resource_request(pod, non_zero=True)
        requested, allocatable = {}, {}
        for name in self.resources:
            if name == "cpu":
                requested[name] = node_info.non_zero_requested.milli_cpu + pod_request.milli_cpu
                allocatable[name] = node_info.allocatable.milli_cpu
            elif name == "memory":
                requested[name] = node_info.non_zero_requested.memory + pod_request.memory
                allocatable[name] = node_info.allocatable.memory
            else:
                requested[name] = node_info.requested.scalar_resources.get(
                    name, 0
                ) + pod_request.scalar_resources.get(name, 0)
                allocatable[name] = node_info.allocatable.scalar_resources.get(name, 0)
        return self._score_from_fractions(requested, allocatable), None

    def _score_from_fractions(self, requested, allocatable) -> int:
        raise NotImplementedError


class BalancedAllocation(_ResourceAllocationScorer):
    NAME = "NodeResourcesBalancedAllocation"

    @staticmethod
    def factory(args, handle):
        return BalancedAllocation(handle)

    def _score_from_fractions(self, requested, allocatable) -> int:
        fractions = []
        for name in self.resources:
            if allocatable[name] == 0:
                return 0
            f = requested[name] / allocatable[name]
            if f >= 1.0:
                # over-committed on a dimension: worst balance
                return 0
            fractions.append(f)
        diff = abs(fractions[0] - fractions[1])
        return int((1.0 - diff) * MAX_NODE_SCORE)


class LeastAllocated(_ResourceAllocationScorer):
    NAME = "NodeResourcesLeastAllocated"

    @staticmethod
    def factory(args, handle):
        p = LeastAllocated(handle)
        p._load_weights(args)
        return p

    def _load_weights(self, args):
        if args and args.get("resources"):
            self.resources = {
                r["name"]: int(r.get("weight", 1)) for r in args["resources"]
            }

    def _score_from_fractions(self, requested, allocatable) -> int:
        total, weight_sum = 0, 0
        for name, weight in self.resources.items():
            if allocatable[name] == 0:
                continue
            free = max(0, allocatable[name] - requested[name])
            total += weight * free * MAX_NODE_SCORE // allocatable[name]
            weight_sum += weight
        return total // weight_sum if weight_sum else 0


class MostAllocated(_ResourceAllocationScorer):
    NAME = "NodeResourcesMostAllocated"

    @staticmethod
    def factory(args, handle):
        p = MostAllocated(handle)
        if args and args.get("resources"):
            p.resources = {
                r["name"]: int(r.get("weight", 1)) for r in args["resources"]
            }
        return p

    def _score_from_fractions(self, requested, allocatable) -> int:
        total, weight_sum = 0, 0
        for name, weight in self.resources.items():
            if allocatable[name] == 0:
                continue
            used = min(requested[name], allocatable[name])
            total += weight * used * MAX_NODE_SCORE // allocatable[name]
            weight_sum += weight
        return total // weight_sum if weight_sum else 0


class RequestedToCapacityRatio(_ResourceAllocationScorer):
    NAME = "RequestedToCapacityRatio"

    @staticmethod
    def factory(args, handle):
        p = RequestedToCapacityRatio(handle)
        args = args or {}
        shape = args.get("shape") or [
            {"utilization": 0, "score": 0},
            {"utilization": 100, "score": 10},
        ]
        p.points = sorted(
            [(int(s["utilization"]), int(s["score"])) for s in shape]
        )
        if args.get("resources"):
            p.resources = {
                r["name"]: int(r.get("weight", 1)) for r in args["resources"]
            }
        return p

    points: List[Tuple[int, int]] = [(0, 0), (100, 10)]

    def _piecewise(self, utilization: float) -> float:
        pts = self.points
        if utilization <= pts[0][0]:
            return pts[0][1]
        for (x0, y0), (x1, y1) in zip(pts, pts[1:]):
            if utilization <= x1:
                return y0 + (y1 - y0) * (utilization - x0) / (x1 - x0)
        return pts[-1][1]

    def _score_from_fractions(self, requested, allocatable) -> int:
        # shape scores are on a 0-10 scale (reference maxUtilization handling)
        total, weight_sum = 0.0, 0
        for name, weight in self.resources.items():
            if allocatable[name] == 0:
                continue
            utilization = min(100.0, 100.0 * requested[name] / allocatable[name])
            total += weight * self._piecewise(utilization)
            weight_sum += weight
        if weight_sum == 0:
            return 0
        return int(total / weight_sum * MAX_NODE_SCORE / 10)
