"""PodTopologySpread (reference ``plugins/podtopologyspread/`` — 843 LoC,
one of the "big five"):

- PreFilter (filtering.go:198-273) counts matching pods per topology pair
  for each DoNotSchedule constraint, over nodes that pass the incoming
  pod's node affinity/selector, and tracks the per-key minimum.
- Filter (filtering.go:313-324): ``matchNum + selfMatch − minMatchNum ≤ maxSkew``.
- Score (scoring.go:109-253) for ScheduleAnyway constraints: fewer matching
  pods in the node's topology domain → higher score.

The TPU path computes the same counts as a one-hot segment-sum
(``kubernetes_tpu/ops/predicates.py``).
"""

from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.labels import selector_from_label_selector
from kubernetes_tpu.api.types import Pod, TopologySpreadConstraint
from kubernetes_tpu.scheduler.framework.interface import (
    MAX_NODE_SCORE,
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    NodeScore,
    PreFilterExtensions,
    PreFilterPlugin,
    PreScorePlugin,
    ScoreExtensions,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.scheduler.framework.plugins.helpers import (
    pod_matches_node_selector_and_affinity,
)
from kubernetes_tpu.scheduler.types import NodeInfo

PRE_FILTER_STATE_KEY = "PreFilterPodTopologySpread"
PRE_SCORE_STATE_KEY = "PreScorePodTopologySpread"

ERR_REASON = "node(s) didn't match pod topology spread constraints"
ERR_REASON_MISSING_LABEL = ERR_REASON + " (missing required label)"

DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"

TopologyPair = Tuple[str, str]


class _Constraint:
    __slots__ = ("max_skew", "topology_key", "selector")

    def __init__(self, c: TopologySpreadConstraint):
        self.max_skew = c.max_skew
        self.topology_key = c.topology_key
        self.selector = selector_from_label_selector(c.label_selector)

    def matches(self, pod: Pod, namespace: str) -> bool:
        return pod.namespace == namespace and self.selector.matches(
            pod.metadata.labels
        )


class _PreFilterState:
    __slots__ = ("constraints", "tp_counts", "tp_key_domains", "namespace")

    def __init__(self):
        self.constraints: List[_Constraint] = []
        self.tp_counts: Dict[TopologyPair, int] = defaultdict(int)
        # per topology key: the set of values seen on eligible nodes
        # (needed to compute the min even when a domain has zero matches)
        self.tp_key_domains: Dict[str, set] = defaultdict(set)
        self.namespace = ""

    def clone(self) -> "_PreFilterState":
        c = _PreFilterState()
        c.constraints = self.constraints
        c.tp_counts = defaultdict(int, self.tp_counts)
        c.tp_key_domains = defaultdict(set, {
            k: set(v) for k, v in self.tp_key_domains.items()
        })
        c.namespace = self.namespace
        return c

    def min_match(self, key: str) -> int:
        domains = self.tp_key_domains.get(key)
        if not domains:
            return 0
        return min(self.tp_counts.get((key, v), 0) for v in domains)

    def update(self, pod: Pod, node, sign: int) -> None:
        labels = node.metadata.labels
        for c in self.constraints:
            if c.topology_key not in labels:
                continue
            if c.matches(pod, self.namespace):
                self.tp_counts[(c.topology_key, labels[c.topology_key])] += sign


def _pod_constraints(pod: Pod, action: str) -> List[_Constraint]:
    return [
        _Constraint(c)
        for c in pod.spec.topology_spread_constraints
        if c.when_unsatisfiable == action and c.topology_key
    ]


class PodTopologySpread(
    PreFilterPlugin, FilterPlugin, PreScorePlugin, ScorePlugin
):
    NAME = "PodTopologySpread"

    @staticmethod
    def factory(args, handle):
        return PodTopologySpread(handle, args or {})

    def __init__(self, handle=None, args=None):
        self.handle = handle
        args = args or {}
        self.default_constraints = [
            TopologySpreadConstraint.from_dict(c)
            for c in (args.get("defaultConstraints") or [])
        ]

    # ------------------------------------------------------------------
    def pre_filter(self, state, pod: Pod) -> Optional[Status]:
        s = _PreFilterState()
        s.namespace = pod.namespace
        s.constraints = _pod_constraints(pod, DO_NOT_SCHEDULE)
        if not s.constraints and self.default_constraints:
            s.constraints = [
                _Constraint(c)
                for c in self.default_constraints
                if c.when_unsatisfiable == DO_NOT_SCHEDULE
            ]
        if s.constraints:
            for ni in self.handle.snapshot().list():
                node = ni.node
                if node is None:
                    continue
                # only nodes the incoming pod could land on count toward
                # skew (filtering.go: nodeAffinity pre-check)
                if not pod_matches_node_selector_and_affinity(pod, node):
                    continue
                labels = node.metadata.labels
                for c in s.constraints:
                    if c.topology_key not in labels:
                        continue
                    value = labels[c.topology_key]
                    s.tp_key_domains[c.topology_key].add(value)
                    count = sum(
                        1
                        for pi in ni.pods
                        if pi.pod.metadata.deletion_timestamp is None
                        and c.matches(pi.pod, s.namespace)
                    )
                    if count:
                        s.tp_counts[(c.topology_key, value)] += count
        state.write(PRE_FILTER_STATE_KEY, s)
        return None

    def pre_filter_extensions(self):
        return _Extensions()

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        try:
            s: _PreFilterState = state.read(PRE_FILTER_STATE_KEY)
        except KeyError:
            return Status(1, "reading PodTopologySpread prefilter state")
        if not s.constraints:
            return None
        labels = node_info.node.metadata.labels
        for c in s.constraints:
            if c.topology_key not in labels:
                return Status(
                    UNSCHEDULABLE_AND_UNRESOLVABLE, ERR_REASON_MISSING_LABEL
                )
            value = labels[c.topology_key]
            self_match = 1 if c.selector.matches(pod.metadata.labels) else 0
            match_num = s.tp_counts.get((c.topology_key, value), 0)
            skew = match_num + self_match - s.min_match(c.topology_key)
            if skew > c.max_skew:
                return Status(UNSCHEDULABLE, ERR_REASON)
        return None

    # ------------------------------------------------------------------
    def pre_score(self, state, pod: Pod, nodes: List) -> Optional[Status]:
        constraints = _pod_constraints(pod, SCHEDULE_ANYWAY)
        if not constraints and self.default_constraints:
            constraints = [
                _Constraint(c)
                for c in self.default_constraints
                if c.when_unsatisfiable == SCHEDULE_ANYWAY
            ]
        counts: Dict[TopologyPair, int] = defaultdict(int)
        ignored_nodes = set()
        if constraints:
            for ni in self.handle.snapshot().list():
                node = ni.node
                if node is None:
                    continue
                labels = node.metadata.labels
                if any(c.topology_key not in labels for c in constraints):
                    ignored_nodes.add(node.name)
                    continue
                for c in constraints:
                    value = labels[c.topology_key]
                    count = sum(
                        1
                        for pi in ni.pods
                        if pi.pod.metadata.deletion_timestamp is None
                        and c.matches(pi.pod, pod.namespace)
                    )
                    counts[(c.topology_key, value)] += count
        state.write(
            PRE_SCORE_STATE_KEY, (constraints, counts, ignored_nodes)
        )
        return None

    def score(self, state, pod: Pod, node_name: str) -> Tuple[int, Optional[Status]]:
        node_info = self.handle.snapshot().get(node_name)
        if node_info is None or node_info.node is None:
            return 0, Status(1, f"node {node_name} not found")
        try:
            constraints, counts, ignored = state.read(PRE_SCORE_STATE_KEY)
        except KeyError:
            return 0, None
        if not constraints or node_name in ignored:
            return 0, None
        labels = node_info.node.metadata.labels
        total = 0
        for c in constraints:
            value = labels.get(c.topology_key)
            if value is not None:
                total += counts.get((c.topology_key, value), 0)
        return total, None

    def score_extensions(self):
        return _Normalize()


class _Normalize(ScoreExtensions):
    def normalize_score(self, state, pod, scores: List[NodeScore]):
        """Fewer matching pods in the domain → higher score (inverted
        min-max, scoring.go NormalizeScore)."""
        try:
            constraints, _, ignored = state.read(PRE_SCORE_STATE_KEY)
        except KeyError:
            return None
        if not constraints:
            return None
        relevant = [s for s in scores if s.name not in ignored]
        if not relevant:
            return None
        max_s = max(s.score for s in relevant)
        min_s = min(s.score for s in relevant)
        spread = max_s - min_s
        for s in scores:
            if s.name in ignored:
                s.score = 0
                continue
            if spread == 0:
                s.score = MAX_NODE_SCORE
            else:
                s.score = int(MAX_NODE_SCORE * (max_s - s.score) / spread)
        return None


class _Extensions(PreFilterExtensions):
    def add_pod(self, state, pod_to_schedule, pod_to_add, node_info):
        s: _PreFilterState = state.read(PRE_FILTER_STATE_KEY)
        if node_info.node is not None and pod_matches_node_selector_and_affinity(
            pod_to_schedule, node_info.node
        ):
            s.update(pod_to_add, node_info.node, +1)
        return None

    def remove_pod(self, state, pod_to_schedule, pod_to_remove, node_info):
        s: _PreFilterState = state.read(PRE_FILTER_STATE_KEY)
        if node_info.node is not None and pod_matches_node_selector_and_affinity(
            pod_to_schedule, node_info.node
        ):
            s.update(pod_to_remove, node_info.node, -1)
        return None
