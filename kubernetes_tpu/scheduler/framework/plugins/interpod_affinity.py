"""InterPodAffinity (reference ``plugins/interpodaffinity/`` — 754 LoC, one
of the "big five"):

- PreFilter (filtering.go:162-235) builds topology-pair → match-count maps
  over all nodes: (1) existing pods' *required anti-affinity* terms that
  match the incoming pod, (2) existing pods matched by the incoming pod's
  required affinity terms, (3) by its required anti-affinity terms.
- Filter (filtering.go:313-374) is then O(terms) map lookups per node.
- PreScore/Score (scoring.go:129-282) accumulate weighted preferred-term
  matches per topology pair, min-max normalized.

The TPU path re-derives these maps as segment-sums over a [pods × terms]
match matrix (see ``kubernetes_tpu/ops/predicates.py``).
"""

from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    MAX_NODE_SCORE,
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    NodeScore,
    PreFilterExtensions,
    PreFilterPlugin,
    PreScorePlugin,
    ScoreExtensions,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo

PRE_FILTER_STATE_KEY = "PreFilterInterPodAffinity"
PRE_SCORE_STATE_KEY = "PreScoreInterPodAffinity"

ERR_EXISTING_ANTI_AFFINITY = (
    "node(s) didn't satisfy existing pods anti-affinity rules"
)
ERR_ANTI_AFFINITY = "node(s) didn't match pod anti-affinity rules"
ERR_AFFINITY = "node(s) didn't match pod affinity rules"

TopologyPair = Tuple[str, str]


class _PreFilterState:
    __slots__ = (
        "existing_anti_affinity_counts",
        "affinity_counts",
        "anti_affinity_counts",
        "pod_info",
    )

    def __init__(self):
        self.existing_anti_affinity_counts: Dict[TopologyPair, int] = defaultdict(int)
        self.affinity_counts: Dict[TopologyPair, int] = defaultdict(int)
        self.anti_affinity_counts: Dict[TopologyPair, int] = defaultdict(int)
        self.pod_info: Optional[PodInfo] = None

    def clone(self) -> "_PreFilterState":
        c = _PreFilterState()
        c.existing_anti_affinity_counts = defaultdict(
            int, self.existing_anti_affinity_counts
        )
        c.affinity_counts = defaultdict(int, self.affinity_counts)
        c.anti_affinity_counts = defaultdict(int, self.anti_affinity_counts)
        c.pod_info = self.pod_info
        return c

    def update_existing_anti_affinity(self, existing: PodInfo, node, sign: int) -> None:
        """Existing pod's required anti-affinity terms vs the incoming pod."""
        incoming = self.pod_info
        labels = node.metadata.labels
        for term in existing.required_anti_affinity_terms:
            if term.topology_key in labels and term.matches(incoming.pod):
                self.existing_anti_affinity_counts[
                    (term.topology_key, labels[term.topology_key])
                ] += sign

    def update(self, existing: PodInfo, node, sign: int) -> None:
        """Apply one existing pod's full contribution (reference
        updateWithPod; used by the AddPod/RemovePod extensions)."""
        incoming = self.pod_info
        labels = node.metadata.labels
        self.update_existing_anti_affinity(existing, node, sign)
        # incoming's terms vs existing pod
        for term in incoming.required_affinity_terms:
            if term.topology_key in labels and term.matches(existing.pod):
                self.affinity_counts[
                    (term.topology_key, labels[term.topology_key])
                ] += sign
        for term in incoming.required_anti_affinity_terms:
            if term.topology_key in labels and term.matches(existing.pod):
                self.anti_affinity_counts[
                    (term.topology_key, labels[term.topology_key])
                ] += sign


class InterPodAffinity(
    PreFilterPlugin, FilterPlugin, PreScorePlugin, ScorePlugin
):
    NAME = "InterPodAffinity"

    @staticmethod
    def factory(args, handle):
        return InterPodAffinity(handle, args or {})

    def __init__(self, handle=None, args=None):
        self.handle = handle
        self.hard_pod_affinity_weight = int(
            (args or {}).get("hardPodAffinityWeight", 1)
        )

    # ------------------------------------------------------------------
    def pre_filter(self, state, pod: Pod) -> Optional[Status]:
        snapshot = self.handle.snapshot()
        s = _PreFilterState()
        s.pod_info = PodInfo(pod)
        # pass 1: existing required anti-affinity (affinity-specialized list)
        for ni in snapshot.have_pods_with_required_anti_affinity_list():
            if ni.node is None:
                continue
            for existing in ni.pods_with_required_anti_affinity:
                s.update_existing_anti_affinity(existing, ni.node, +1)
        # pass 2: incoming's required terms vs every pod (all nodes)
        if s.pod_info.required_affinity_terms or s.pod_info.required_anti_affinity_terms:
            for ni in snapshot.list():
                if ni.node is None:
                    continue
                labels = ni.node.metadata.labels
                for existing in ni.pods:
                    incoming = s.pod_info
                    for term in incoming.required_affinity_terms:
                        if term.topology_key in labels and term.matches(existing.pod):
                            s.affinity_counts[
                                (term.topology_key, labels[term.topology_key])
                            ] += 1
                    for term in incoming.required_anti_affinity_terms:
                        if term.topology_key in labels and term.matches(existing.pod):
                            s.anti_affinity_counts[
                                (term.topology_key, labels[term.topology_key])
                            ] += 1
        state.write(PRE_FILTER_STATE_KEY, s)
        return None

    def pre_filter_extensions(self):
        return _Extensions()

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        try:
            s: _PreFilterState = state.read(PRE_FILTER_STATE_KEY)
        except KeyError:
            return Status(1, "reading InterPodAffinity prefilter state")
        labels = node_info.node.metadata.labels

        # 1. existing pods' anti-affinity must not fire on this node
        for (key, value), count in s.existing_anti_affinity_counts.items():
            if count > 0 and labels.get(key) == value:
                return Status(UNSCHEDULABLE, ERR_EXISTING_ANTI_AFFINITY)

        # 2. incoming pod's anti-affinity
        for term in s.pod_info.required_anti_affinity_terms:
            value = labels.get(term.topology_key)
            if value is not None and s.anti_affinity_counts.get(
                (term.topology_key, value), 0
            ) > 0:
                return Status(UNSCHEDULABLE, ERR_ANTI_AFFINITY)

        # 3. incoming pod's affinity: every term must be satisfied here
        if s.pod_info.required_affinity_terms:
            satisfied = all(
                term.topology_key in labels
                and s.affinity_counts.get(
                    (term.topology_key, labels[term.topology_key]), 0
                )
                > 0
                for term in s.pod_info.required_affinity_terms
            )
            if not satisfied:
                # special case (filtering.go): allow the FIRST pod of a
                # self-selecting group to land anywhere
                matches_self = all(
                    term.matches(pod) for term in s.pod_info.required_affinity_terms
                )
                no_matches_anywhere = all(
                    c == 0 for c in s.affinity_counts.values()
                )
                if not (matches_self and no_matches_anywhere):
                    return Status(UNSCHEDULABLE, ERR_AFFINITY)
        return None

    # ------------------------------------------------------------------
    def pre_score(self, state, pod: Pod, nodes: List) -> Optional[Status]:
        incoming = PodInfo(pod)
        has_preferred = bool(
            incoming.preferred_affinity_terms or incoming.preferred_anti_affinity_terms
        )
        score_map: Dict[TopologyPair, float] = defaultdict(float)
        snapshot = self.handle.snapshot()
        # choose the smaller iteration set when the incoming pod has no
        # preferred terms (only existing pods' terms can contribute)
        node_infos = snapshot.list() if has_preferred else snapshot.have_pods_with_affinity_list()
        for ni in node_infos:
            if ni.node is None:
                continue
            labels = ni.node.metadata.labels
            existing_list = ni.pods if has_preferred else ni.pods_with_affinity
            for existing in existing_list:
                self._process_existing(incoming, existing, labels, score_map)
        state.write(PRE_SCORE_STATE_KEY, score_map)
        return None

    def _process_existing(self, incoming: PodInfo, existing: PodInfo, labels,
                          score_map) -> None:
        for wt in incoming.preferred_affinity_terms:
            if wt.term.topology_key in labels and wt.term.matches(existing.pod):
                score_map[(wt.term.topology_key, labels[wt.term.topology_key])] += wt.weight
        for wt in incoming.preferred_anti_affinity_terms:
            if wt.term.topology_key in labels and wt.term.matches(existing.pod):
                score_map[(wt.term.topology_key, labels[wt.term.topology_key])] -= wt.weight
        if self.hard_pod_affinity_weight > 0:
            for term in existing.required_affinity_terms:
                if term.topology_key in labels and term.matches(incoming.pod):
                    score_map[(term.topology_key, labels[term.topology_key])] += (
                        self.hard_pod_affinity_weight
                    )
        for wt in existing.preferred_affinity_terms:
            if wt.term.topology_key in labels and wt.term.matches(incoming.pod):
                score_map[(wt.term.topology_key, labels[wt.term.topology_key])] += wt.weight
        for wt in existing.preferred_anti_affinity_terms:
            if wt.term.topology_key in labels and wt.term.matches(incoming.pod):
                score_map[(wt.term.topology_key, labels[wt.term.topology_key])] -= wt.weight

    def score(self, state, pod: Pod, node_name: str) -> Tuple[int, Optional[Status]]:
        node_info = self.handle.snapshot().get(node_name)
        if node_info is None or node_info.node is None:
            return 0, Status(1, f"node {node_name} not found")
        try:
            score_map = state.read(PRE_SCORE_STATE_KEY)
        except KeyError:
            return 0, None
        labels = node_info.node.metadata.labels
        total = 0.0
        for (key, value), val in score_map.items():
            if labels.get(key) == value:
                total += val
        return int(total), None

    def score_extensions(self):
        return _Normalize()


class _Normalize(ScoreExtensions):
    def normalize_score(self, state, pod, scores: List[NodeScore]):
        if not scores:
            return None
        max_s = max(s.score for s in scores)
        min_s = min(s.score for s in scores)
        spread = max_s - min_s
        for s in scores:
            if spread == 0:
                s.score = 0 if max_s == 0 else MAX_NODE_SCORE
            else:
                s.score = int(MAX_NODE_SCORE * (s.score - min_s) / spread)
        return None


class _Extensions(PreFilterExtensions):
    """Incremental updates for nominated pods / preemption victims
    (filtering.go AddPod/RemovePod)."""

    def add_pod(self, state, pod_to_schedule, pod_to_add, node_info):
        s: _PreFilterState = state.read(PRE_FILTER_STATE_KEY)
        if node_info.node is not None:
            s.update(PodInfo(pod_to_add), node_info.node, +1)
        return None

    def remove_pod(self, state, pod_to_schedule, pod_to_remove, node_info):
        s: _PreFilterState = state.read(PRE_FILTER_STATE_KEY)
        if node_info.node is not None:
            s.update(PodInfo(pod_to_remove), node_info.node, -1)
        return None
