"""NodeName filter (reference ``plugins/nodename/node_name.go``)."""

from typing import Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo

ERR_REASON = "node(s) didn't match the requested hostname"


class NodeName(FilterPlugin):
    NAME = "NodeName"

    @staticmethod
    def factory(args, handle):
        return NodeName()

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        if pod.spec.node_name and pod.spec.node_name != node_info.node.name:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, ERR_REASON)
        return None
