"""NodeUnschedulable filter (reference
``plugins/nodeunschedulable/node_unschedulable.go``): respects
``.spec.unschedulable`` unless the pod tolerates the synthetic
unschedulable taint."""

from typing import Optional

from kubernetes_tpu.api.types import NO_SCHEDULE, Pod, Taint
from kubernetes_tpu.scheduler.framework.interface import (
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo

ERR_REASON_UNSCHEDULABLE = "node(s) were unschedulable"
ERR_REASON_UNKNOWN_CONDITION = "node(s) had unknown conditions"
TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"


class NodeUnschedulable(FilterPlugin):
    NAME = "NodeUnschedulable"

    @staticmethod
    def factory(args, handle):
        return NodeUnschedulable()

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, ERR_REASON_UNKNOWN_CONDITION)
        if not node_info.node.spec.unschedulable:
            return None
        taint = Taint(TAINT_NODE_UNSCHEDULABLE, "", NO_SCHEDULE)
        if any(t.tolerates(taint) for t in pod.spec.tolerations):
            return None
        return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, ERR_REASON_UNSCHEDULABLE)
