"""VolumeRestrictions filter (reference
``plugins/volumerestrictions/volume_restrictions.go``): exclusivity rules —
a GCE PD / AWS EBS volume may not be used read-write by two pods on the same
node; RBD/ISCSI images are node-exclusive."""

from typing import Optional

from kubernetes_tpu.api.types import Pod, Volume
from kubernetes_tpu.scheduler.framework.interface import (
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo

ERR_REASON_DISK_CONFLICT = "node(s) had no available disk"


def _volume_ids(vol: Volume):
    if vol.gce_persistent_disk:
        yield ("gce", vol.gce_persistent_disk)
    if vol.aws_elastic_block_store:
        yield ("aws", vol.aws_elastic_block_store)
    if vol.rbd:
        yield ("rbd", f"{vol.rbd.get('pool', 'rbd')}/{vol.rbd.get('image', '')}")
    if vol.iscsi:
        yield (
            "iscsi",
            f"{vol.iscsi.get('targetPortal', '')}/{vol.iscsi.get('iqn', '')}/"
            f"{vol.iscsi.get('lun', 0)}",
        )


class VolumeRestrictions(FilterPlugin):
    NAME = "VolumeRestrictions"

    @staticmethod
    def factory(args, handle):
        return VolumeRestrictions()

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        wanted = {vid for v in pod.spec.volumes for vid in _volume_ids(v)}
        if not wanted:
            return None
        for pi in node_info.pods:
            for v in pi.pod.spec.volumes:
                for vid in _volume_ids(v):
                    if vid in wanted:
                        return Status(
                            UNSCHEDULABLE_AND_UNRESOLVABLE, ERR_REASON_DISK_CONFLICT
                        )
        return None
