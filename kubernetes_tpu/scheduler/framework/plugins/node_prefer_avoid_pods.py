"""NodePreferAvoidPods score (reference
``plugins/nodepreferavoidpods/node_prefer_avoid_pods.go``): node annotation
``scheduler.alpha.kubernetes.io/preferAvoidPods`` lists controllers whose
pods should avoid the node; weight 10000 in the default provider
(registry.go:126) so it dominates other scores."""

import json
from typing import Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    MAX_NODE_SCORE,
    ScorePlugin,
    Status,
)

ANNOTATION_KEY = "scheduler.alpha.kubernetes.io/preferAvoidPods"


class NodePreferAvoidPods(ScorePlugin):
    NAME = "NodePreferAvoidPods"

    @staticmethod
    def factory(args, handle):
        return NodePreferAvoidPods(handle)

    def __init__(self, handle=None):
        self.handle = handle

    def score(self, state, pod: Pod, node_name: str) -> Tuple[int, Optional[Status]]:
        node_info = self.handle.snapshot().get(node_name)
        if node_info is None or node_info.node is None:
            return 0, Status(1, f"node {node_name} not found")
        node = node_info.node
        controller = None
        for ref in pod.metadata.owner_references:
            if ref.get("controller") or ref.get("kind") in (
                "ReplicationController",
                "ReplicaSet",
            ):
                controller = ref
                break
        if controller is None:
            return MAX_NODE_SCORE, None
        raw = node.metadata.annotations.get(ANNOTATION_KEY)
        if not raw:
            return MAX_NODE_SCORE, None
        try:
            avoids = json.loads(raw).get("preferAvoidPods", [])
        except (ValueError, AttributeError):
            return MAX_NODE_SCORE, None
        for avoid in avoids:
            ref = (avoid.get("podSignature") or {}).get("podController") or {}
            if ref.get("kind") == controller.get("kind") and (
                not ref.get("uid") or ref.get("uid") == controller.get("uid")
            ):
                return 0, None
        return MAX_NODE_SCORE, None
