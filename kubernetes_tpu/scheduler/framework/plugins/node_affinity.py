"""NodeAffinity filter+score (reference
``plugins/nodeaffinity/node_affinity.go``): required terms filter, preferred
terms score (weights summed, min-max normalized)."""

from typing import List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    MAX_NODE_SCORE,
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    NodeScore,
    ScoreExtensions,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.scheduler.framework.plugins.helpers import (
    default_normalize_score,
    node_selector_term_matches,
    pod_matches_node_selector_and_affinity,
)
from kubernetes_tpu.scheduler.types import NodeInfo

ERR_REASON = "node(s) didn't match node selector"


class NodeAffinity(FilterPlugin, ScorePlugin):
    NAME = "NodeAffinity"

    @staticmethod
    def factory(args, handle):
        return NodeAffinity(handle)

    def __init__(self, handle=None):
        self.handle = handle

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        if not pod_matches_node_selector_and_affinity(pod, node_info.node):
            return Status(UNSCHEDULABLE, ERR_REASON)
        return None

    def score(self, state, pod: Pod, node_name: str) -> Tuple[int, Optional[Status]]:
        node_info = self.handle.snapshot().get(node_name)
        if node_info is None or node_info.node is None:
            return 0, Status(1, f"node {node_name} not found")
        node = node_info.node
        count = 0
        aff = pod.spec.affinity
        if aff is not None and aff.node_affinity is not None:
            for term in aff.node_affinity.preferred_during_scheduling_ignored_during_execution:
                if term.weight and node_selector_term_matches(term.preference, node):
                    count += term.weight
        return count, None

    def score_extensions(self):
        return _Normalize()


class _Normalize(ScoreExtensions):
    def normalize_score(self, state, pod, scores: List[NodeScore]):
        default_normalize_score(MAX_NODE_SCORE, False, scores)
        return None
