"""DefaultPreemption PostFilter (reference
``plugins/defaultpreemption/default_preemption.go`` — 814 LoC; call stack in
SURVEY.md section 3.3):

preempt → eligibility check → FindCandidates (dry-run victim selection per
candidate node, PDB-aware) → SelectCandidate (pickOneNodeForPreemption's
criteria chain) → PrepareCandidate (delete victims, clear stale lower-
priority nominations) → return the nominated node name.

The dry run clones NodeInfo+CycleState, removes lower-priority pods via the
PreFilterExtensions RemovePod path, re-runs filters, then re-adds victims
in priority order to minimize evictions (selectVictimsOnNode :600).
"""

import random
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    NodeToStatusMap,
    PostFilterPlugin,
    PostFilterResult,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo


class _Candidate:
    __slots__ = ("node_name", "victims", "num_pdb_violations")

    def __init__(self, node_name: str, victims: List[Pod], num_pdb_violations: int):
        self.node_name = node_name
        self.victims = victims
        self.num_pdb_violations = num_pdb_violations


class DefaultPreemption(PostFilterPlugin):
    NAME = "DefaultPreemption"

    @staticmethod
    def factory(args, handle):
        return DefaultPreemption(handle, args or {})

    def __init__(self, handle=None, args=None):
        args = args or {}
        self.handle = handle
        self.min_candidate_nodes_percentage = int(
            args.get("minCandidateNodesPercentage", 10)
        )
        self.min_candidate_nodes_absolute = int(
            args.get("minCandidateNodesAbsolute", 100)
        )

    # ------------------------------------------------------------------
    def post_filter(self, state, pod: Pod, statuses: NodeToStatusMap):
        client = self.handle.client
        # re-fetch: the pod object may be stale (default_preemption.go:128)
        fresh = client.get_pod(pod.namespace, pod.name)
        if fresh is not None:
            pod = fresh
        if not self._eligible_to_preempt_others(pod):
            return None, Status(
                UNSCHEDULABLE, "preemption is not helpful for scheduling"
            )
        candidates = self._find_candidates(state, pod, statuses)
        if not candidates:
            return None, Status(UNSCHEDULABLE, "no preemption victims found")
        best = self._select_candidate(candidates)
        status = self._prepare_candidate(best, pod)
        if status is not None:
            return None, status
        return PostFilterResult(nominated_node_name=best.node_name), None

    # ------------------------------------------------------------------
    def _eligible_to_preempt_others(self, pod: Pod) -> bool:
        """default_preemption.go:246 PodEligibleToPreemptOthers."""
        return pod_eligible_to_preempt_others(pod, self.handle.snapshot())

    # CycleState key for batch-computed candidate hints (the sidecar's
    # vectorized preemption screen, scheduler/preemption_screen.py)
    HINTS_KEY = "DefaultPreemption/candidate-hints"
    # with trusted hints, a handful of validated candidates suffices —
    # the screen already ranked the whole cluster
    HINTED_DRY_RUNS = 4

    def _find_candidates(
        self, state, pod: Pod, statuses: NodeToStatusMap
    ) -> List[_Candidate]:
        snapshot = self.handle.snapshot()
        try:
            hints = state.read(self.HINTS_KEY)
        except KeyError:
            hints = None
        if hints:
            candidates = self._dry_run_hints(state, pod, statuses,
                                             snapshot, hints)
            if candidates:
                return candidates
            # stale/empty hints: fall through to the unpruned scan
        # nodes where preemption might help: everything not marked
        # UnschedulableAndUnresolvable (:274 nodesWherePreemptionMightHelp)
        potential = [
            ni
            for ni in snapshot.list()
            if ni.node is not None
            and (
                statuses.get(ni.node.name) is None
                or statuses[ni.node.name].code != UNSCHEDULABLE_AND_UNRESOLVABLE
            )
        ]
        pdbs = self.handle.client.list_pdbs()
        # getOffsetAndNumCandidates (default_preemption.go:195): dry-run
        # from a random offset, stopping once enough candidates are found
        # (>= max(n * MinCandidateNodesPercentage%, ...Absolute)) — the
        # adaptive-sampling analog for preemption; evaluating all nodes
        # is both off-spec and quadratic under mass preemption
        n = len(potential)
        if n == 0:
            return []
        num_candidates = min(
            max(
                n * self.min_candidate_nodes_percentage // 100,
                self.min_candidate_nodes_absolute,
            ),
            n,
        )
        offset = random.randrange(n)
        candidates = []
        non_violating_found = False
        for k in range(n):
            ni = potential[(offset + k) % n]
            result = self._select_victims_on_node(state, pod, ni, pdbs)
            if result is not None:
                victims, violations = result
                candidates.append(
                    _Candidate(ni.node.name, victims, violations)
                )
                if violations == 0:
                    non_violating_found = True
                # upstream only cancels the dry-run once a PDB-NON-
                # violating candidate exists (dryRunPreemption keeps
                # scanning otherwise), so a run of violating-only nodes
                # after the offset cannot force a needless PDB break
                if len(candidates) >= num_candidates and non_violating_found:
                    break
        return candidates

    def _dry_run_hints(self, state, pod: Pod, statuses: NodeToStatusMap,
                       snapshot, hints) -> List[_Candidate]:
        """Dry-run the batch screen's ranked candidates (full filter
        fidelity — the screen only pruned). Stops once a few validated
        candidates exist with at least one PDB-non-violating choice."""
        pdbs = self.handle.client.list_pdbs()
        candidates: List[_Candidate] = []
        non_violating_found = False
        for name in hints:
            st = statuses.get(name)
            if st is not None and st.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue
            ni = snapshot.get(name)
            if ni is None or ni.node is None:
                continue
            result = self._select_victims_on_node(state, pod, ni, pdbs)
            if result is None:
                continue
            victims, violations = result
            candidates.append(_Candidate(name, victims, violations))
            if violations == 0:
                non_violating_found = True
            if len(candidates) >= self.HINTED_DRY_RUNS and \
                    non_violating_found:
                break
        return candidates

    def _select_victims_on_node(
        self, state, pod: Pod, node_info: NodeInfo, pdbs
    ) -> Optional[Tuple[List[Pod], int]]:
        """default_preemption.go:600 selectVictimsOnNode."""
        fwk = self.handle
        node_copy = node_info.clone()
        state_copy = state.clone()

        potential_victims = [
            pi.pod for pi in node_copy.pods if pi.pod.priority() < pod.priority()
        ]
        if not potential_victims:
            return None

        for victim in potential_victims:
            node_copy.remove_pod(victim)
            fwk.run_pre_filter_extension_remove_pod(state_copy, pod, victim, node_copy)

        status = fwk.run_filter_plugins_with_nominated_pods(state_copy, pod, node_copy)
        if not Status.is_ok(status):
            return None

        violating, non_violating = _split_pods_by_pdb_violation(potential_victims, pdbs)
        victims: List[Pod] = []
        num_violations = 0

        def reprieve(victim: Pod) -> bool:
            """Try to keep this pod; re-add it and check filters still pass."""
            node_copy.add_pod(victim)
            fwk.run_pre_filter_extension_add_pod(state_copy, pod, victim, node_copy)
            s = fwk.run_filter_plugins_with_nominated_pods(state_copy, pod, node_copy)
            if Status.is_ok(s):
                return True
            node_copy.remove_pod(victim)
            fwk.run_pre_filter_extension_remove_pod(state_copy, pod, victim, node_copy)
            return False

        # re-add by descending priority; PDB-violating candidates first so
        # they're the most likely to be reprieved
        for victim in sorted(violating, key=lambda p: -p.priority()):
            if not reprieve(victim):
                victims.append(victim)
                num_violations += 1
        for victim in sorted(non_violating, key=lambda p: -p.priority()):
            if not reprieve(victim):
                victims.append(victim)
        if not victims:
            return None
        return victims, num_violations

    # ------------------------------------------------------------------
    @staticmethod
    def _select_candidate(candidates: List[_Candidate]) -> _Candidate:
        """default_preemption.go:465 pickOneNodeForPreemption criteria
        chain: fewest PDB violations → lowest max victim priority → smallest
        priority sum → fewest victims → stable order."""

        def key(c: _Candidate):
            priorities = [v.priority() for v in c.victims]
            return (
                c.num_pdb_violations,
                max(priorities, default=0),
                sum(priorities),
                len(c.victims),
            )

        return min(candidates, key=key)

    def _prepare_candidate(self, candidate: _Candidate, pod: Pod) -> Optional[Status]:
        """default_preemption.go:698 PrepareCandidate: evict victims, clear
        stale nominations of lower-priority pods on the chosen node."""
        client = self.handle.client
        recorder = getattr(self.handle, "event_recorder", None)
        for victim in candidate.victims:
            # a waiting (Permit-parked) victim is rejected instead of deleted
            if not self.handle.reject_waiting_pod(victim.uid):
                try:
                    client.delete_pod(victim.namespace, victim.name)
                except Exception as e:
                    return Status(1, f"deleting victim {victim.full_name()}: {e}")
            if recorder is not None:
                # default_preemption.go:698: "Preempted by ... on node ..."
                recorder.event(
                    victim, "Normal", "Preempted",
                    f"Preempted by {pod.namespace}/{pod.metadata.name} on "
                    f"node {candidate.node_name}",
                )
        nominator = self.handle.pod_nominator
        if nominator is not None:
            for pi in list(nominator.nominated_pods_for_node(candidate.node_name)):
                if pi.pod.priority() < pod.priority():
                    nominator.delete_nominated_pod_if_exists(pi.pod)
                    client.clear_nominated_node_name(pi.pod.namespace, pi.pod.name)
        return None


def pdb_covers(pod: Pod, pdb) -> bool:
    """Does this PDB select this pod? The single matching predicate
    shared by the dry-run's violation split and the batch planner's
    conservative victim exclusion."""
    return pdb.namespace == pod.namespace and \
        pdb.selector.matches(pod.metadata.labels)


def pod_eligible_to_preempt_others(pod: Pod, snapshot=None) -> bool:
    """default_preemption.go:246 PodEligibleToPreemptOthers — shared by
    the serial PostFilter and the batch victim planner (the two must
    gate identically or the batch path evicts for pods the reference
    would refuse, e.g. preemptionPolicy Never)."""
    if pod.spec.preemption_policy == "Never":
        return False
    nominated = pod.status.nominated_node_name
    if nominated and snapshot is not None:
        ni = snapshot.get(nominated)
        if ni is not None:
            # a previous preemption is still playing out: wait for it
            if any(
                pi.pod.metadata.deletion_timestamp is not None
                and pi.pod.priority() < pod.priority()
                for pi in ni.pods
            ):
                return False
    return True


def _split_pods_by_pdb_violation(pods: List[Pod], pdbs) -> Tuple[List[Pod], List[Pod]]:
    """Pods whose eviction would violate a PodDisruptionBudget (reference
    filterPodsWithPDBViolation)."""
    violating, non_violating = [], []
    for pod in pods:
        violates = any(
            pdb_covers(pod, pdb) and pdb.disruptions_allowed <= 0
            for pdb in pdbs
        )
        if violates:
            violating.append(pod)
        else:
            non_violating.append(pod)
    return violating, non_violating
