"""ServiceAffinity (legacy; reference
``plugins/serviceaffinity/service_affinity.go``): co-locates pods of the
same Service on nodes sharing the configured label values (args
``affinityLabels``), and optionally spreads by ``antiAffinityLabelsPreference``."""

from typing import List, Optional, Tuple

from kubernetes_tpu.api.labels import Selector
from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    MAX_NODE_SCORE,
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    NodeScore,
    PreFilterExtensions,
    PreFilterPlugin,
    ScoreExtensions,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.scheduler.framework.plugins.helpers import default_normalize_score
from kubernetes_tpu.scheduler.types import NodeInfo

PRE_FILTER_STATE_KEY = "PreFilterServiceAffinity"
ERR_REASON = "node(s) didn't match service affinity"


class _State:
    __slots__ = ("matching_pods",)

    def __init__(self, matching_pods: List[Pod]):
        self.matching_pods = matching_pods

    def clone(self):
        return _State(list(self.matching_pods))


class ServiceAffinity(PreFilterPlugin, FilterPlugin, ScorePlugin):
    NAME = "ServiceAffinity"

    @staticmethod
    def factory(args, handle):
        return ServiceAffinity(handle, args or {})

    def __init__(self, handle=None, args=None):
        args = args or {}
        self.handle = handle
        self.affinity_labels = list(args.get("affinityLabels") or [])
        self.anti_affinity_labels_preference = list(
            args.get("antiAffinityLabelsPreference") or []
        )

    def _service_selectors(self, pod: Pod) -> List[Selector]:
        out = []
        for svc in self.handle.client.list_services(pod.namespace):
            sel = Selector.from_map(svc.selector)
            if not sel.is_empty() and sel.matches(pod.metadata.labels):
                out.append(sel)
        return out

    def pre_filter(self, state, pod: Pod) -> Optional[Status]:
        selectors = self._service_selectors(pod)
        matching: List[Pod] = []
        if selectors:
            for ni in self.handle.snapshot().list():
                for pi in ni.pods:
                    p = pi.pod
                    if p.namespace == pod.namespace and any(
                        sel.matches(p.metadata.labels) for sel in selectors
                    ):
                        matching.append(p)
        state.write(PRE_FILTER_STATE_KEY, _State(matching))
        return None

    def pre_filter_extensions(self):
        return _Extensions(self)

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if not self.affinity_labels:
            return None
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        try:
            s: _State = state.read(PRE_FILTER_STATE_KEY)
        except KeyError:
            return Status(1, "reading ServiceAffinity prefilter state")
        snapshot = self.handle.snapshot()
        # the label values the service's existing pods pin (first pod wins,
        # matching the reference's "first pod determines placement" model)
        pinned = {}
        for p in s.matching_pods:
            if not p.spec.node_name:
                continue
            ni = snapshot.get(p.spec.node_name)
            if ni is None or ni.node is None:
                continue
            for label in self.affinity_labels:
                if label not in pinned and label in ni.node.metadata.labels:
                    pinned[label] = ni.node.metadata.labels[label]
        labels = node_info.node.metadata.labels
        for label in self.affinity_labels:
            if label not in labels:
                return Status(UNSCHEDULABLE, ERR_REASON)
            if label in pinned and labels[label] != pinned[label]:
                return Status(UNSCHEDULABLE, ERR_REASON)
        return None

    def score(self, state, pod: Pod, node_name: str) -> Tuple[int, Optional[Status]]:
        node_info = self.handle.snapshot().get(node_name)
        if node_info is None or node_info.node is None:
            return 0, Status(1, f"node {node_name} not found")
        if not self.anti_affinity_labels_preference:
            return 0, None
        selectors = self._service_selectors(pod)
        if not selectors:
            return 0, None
        count = sum(
            1
            for pi in node_info.pods
            if pi.pod.namespace == pod.namespace
            and any(sel.matches(pi.pod.metadata.labels) for sel in selectors)
        )
        return count, None

    def score_extensions(self):
        return _Normalize()


class _Normalize(ScoreExtensions):
    def normalize_score(self, state, pod, scores: List[NodeScore]):
        default_normalize_score(MAX_NODE_SCORE, True, scores)
        return None


class _Extensions(PreFilterExtensions):
    def __init__(self, plugin: ServiceAffinity):
        self.plugin = plugin

    def add_pod(self, state, pod_to_schedule, pod_to_add, node_info):
        s: _State = state.read(PRE_FILTER_STATE_KEY)
        selectors = self.plugin._service_selectors(pod_to_schedule)
        if pod_to_add.namespace == pod_to_schedule.namespace and any(
            sel.matches(pod_to_add.metadata.labels) for sel in selectors
        ):
            s.matching_pods.append(pod_to_add)
        return None

    def remove_pod(self, state, pod_to_schedule, pod_to_remove, node_info):
        s: _State = state.read(PRE_FILTER_STATE_KEY)
        s.matching_pods = [
            p for p in s.matching_pods if p.uid != pod_to_remove.uid
        ]
        return None
