"""Coscheduling (gang scheduling): Permit plugin + gang-aware QueueSort.

The reference tree has no in-tree equivalent — gang scheduling is the
Permit-phase pattern of the out-of-tree coscheduling plugin, enabled by the
framework's ``RunPermitPlugins``/``WaitOnPermit`` machinery
(``runtime/framework.go:960,1011``; see SURVEY.md section 6). Pods declare a
gang via labels:

    pod-group.scheduling.k8s.io/name: <group>
    pod-group.scheduling.k8s.io/min-available: "<N>"

Behaviors mirrored from the out-of-tree plugin:

- **Permit wait**: a pod whose gang hasn't reached N scheduled-or-waiting
  members Waits at Permit; when the N-th member arrives, every waiting
  member is allowed.
- **Queue-sort co-ordering** (``CoschedulingSort``): pods sort by
  priority, then by their GROUP's anchor timestamp (earliest member seen),
  then by group name — so a gang's members drain consecutively instead of
  interleaving with other gangs. Interleaving is the starvation mode: two
  half-admitted gangs each hold resources at Permit that the other needs.
  Non-gang pods keep exactly the PrioritySort order.
- **Whole-gang rejection + backoff**: when one member fails downstream
  (Permit timeout, bind failure, unreserve), every waiting member of the
  gang is rejected together — partial gangs must not squat on reserved
  resources — and the gang backs off (PreFilter fails fast) before its
  next admission attempt.

BASELINE config #5 exercises this together with spread + fit.
"""

import threading
import time
from typing import Dict, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    UNSCHEDULABLE,
    WAIT,
    PermitPlugin,
    PreFilterPlugin,
    QueueSortPlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import QueuedPodInfo

GROUP_NAME_LABEL = "pod-group.scheduling.k8s.io/name"
MIN_AVAILABLE_LABEL = "pod-group.scheduling.k8s.io/min-available"
DEFAULT_WAIT_SECONDS = 60.0
DEFAULT_GANG_BACKOFF_SECONDS = 5.0


def pod_group(pod: Pod) -> Tuple[str, int]:
    name = pod.metadata.labels.get(GROUP_NAME_LABEL, "")
    try:
        min_available = int(pod.metadata.labels.get(MIN_AVAILABLE_LABEL, "0"))
    except ValueError:
        min_available = 0
    return name, min_available


class CoschedulingSort(QueueSortPlugin):
    """Gang-aware QueueSort: (priority desc, group anchor timestamp,
    group name, own timestamp). The anchor is the earliest timestamp seen
    for the group, so every member sorts where the gang's FIRST member
    sorts and the gang drains as one contiguous run."""

    NAME = "CoschedulingSort"

    # bounded gang-anchor memory across gang lifetimes: least-recently-
    # SIGHTED groups evict first — a still-queued gang keeps being
    # sighted on every sort-key computation, so eviction targets dead
    # groups and never re-keys entries sitting in the active heap
    # (re-anchoring an in-heap group would break the heap invariant)
    MAX_ANCHORS = 4096

    def __init__(self):
        self._lock = threading.Lock()
        # group -> anchor timestamp; dict order doubles as the LRU
        # (move_to_end on every sighting)
        self._anchors: Dict[str, float] = {}

    @staticmethod
    def factory(args, handle):
        return CoschedulingSort()

    def _anchor(self, qpi: QueuedPodInfo) -> Tuple[float, str]:
        group = qpi.pod.metadata.labels.get(GROUP_NAME_LABEL, "")
        if not group:
            return qpi.timestamp, ""
        with self._lock:
            ts = self._anchors.get(group)
            if ts is None:
                # FROZEN at first sighting: a member sighted later with
                # an earlier timestamp (e.g. a requeued pod keeping its
                # original stamp) must NOT re-key the group while
                # siblings sit in the active heap — lowering the anchor
                # of in-heap entries breaks the heap invariant and pops
                # come out mis-ordered until the entries churn
                ts = qpi.timestamp
            # refresh recency (plain dicts preserve insertion order)
            self._anchors.pop(group, None)
            self._anchors[group] = ts
            if len(self._anchors) > self.MAX_ANCHORS:
                drop = len(self._anchors) - self.MAX_ANCHORS + \
                    self.MAX_ANCHORS // 4
                for g in list(self._anchors)[:drop]:
                    del self._anchors[g]
        return ts, group

    def sort_key(self, qpi: QueuedPodInfo) -> tuple:
        ts, group = self._anchor(qpi)
        return (-qpi.pod.priority(), ts, group, qpi.timestamp)

    def less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        return self.sort_key(a) < self.sort_key(b)


class Coscheduling(PermitPlugin, PreFilterPlugin):
    NAME = "Coscheduling"

    @staticmethod
    def factory(args, handle):
        return Coscheduling(handle, args or {})

    def __init__(self, handle=None, args=None):
        self.handle = handle
        args = args or {}
        self.wait_seconds = float(
            args.get("permitWaitSeconds", DEFAULT_WAIT_SECONDS)
        )
        self.backoff_seconds = float(
            args.get("gangBackoffSeconds", DEFAULT_GANG_BACKOFF_SECONDS)
        )
        self._lock = threading.Lock()
        self._permitted: Dict[str, int] = {}  # group -> pods at/past Permit
        self._backoff_until: Dict[str, float] = {}
        # group -> uids of members parked at Permit. Release/reject walk
        # THIS index via handle.get_waiting_pod (dict lookups) instead of
        # iterate_waiting_pods — the generic scan is O(all waiting pods)
        # per release, which is quadratic across a batch full of gangs.
        # Safe because members are parked sequentially by the commit
        # loop before the releasing member's permit() runs.
        self._waiting: Dict[str, set] = {}

    # ------------------------------------------------------------------
    def pre_filter(self, state, pod: Pod):
        """Fail fast while the gang backs off after a failed admission
        attempt — no point running the filter chain (or reserving
        resources) for a gang that just collapsed at Permit."""
        group, min_available = pod_group(pod)
        if not group or min_available <= 1:
            return None
        with self._lock:
            until = self._backoff_until.get(group, 0.0)
        if time.monotonic() < until:
            return Status(
                UNSCHEDULABLE,
                f"gang {group} backing off after a failed admission",
            )
        return None

    def permit(self, state, pod: Pod, node_name: str):
        group, min_available = pod_group(pod)
        if not group or min_available <= 1:
            return None, 0.0
        with self._lock:
            self._permitted[group] = self._permitted.get(group, 0) + 1
            arrived = self._permitted[group]
            if arrived >= min_available:
                members = self._waiting.pop(group, set())
            else:
                self._waiting.setdefault(group, set()).add(pod.uid)
                members = None
        if members is not None:
            # release every gang member parked at Permit
            for uid in members:
                wp = self.handle.get_waiting_pod(uid)
                if wp is not None:
                    wp.allow(self.NAME)
            return None, 0.0
        # activate siblings parked in backoff/unschedulable: the gang
        # completes only if members OVERLAP at Permit, and staggered
        # backoffs would stop that overlap from ever happening
        nominator = getattr(self.handle, "pod_nominator", None)
        if nominator is not None and hasattr(nominator, "gang_members_added"):
            nominator.gang_members_added({group})
        return Status(WAIT, f"waiting for gang {group}"), self.wait_seconds

    def note_member_deleted(self, pod: Pod) -> None:
        """A scheduled (bound) gang member was deleted: release its
        arrival slot so a RE-CREATED gang under the same group name
        starts from zero instead of inheriting the stale count and
        skipping the Permit wait. Zeroed groups drop their bookkeeping
        (bounded state across gang lifetimes)."""
        group, _ = pod_group(pod)
        if not group:
            return
        with self._lock:
            left = self._permitted.get(group)
            if left is not None:
                left -= 1
                if left <= 0:
                    self._permitted.pop(group, None)
                    self._backoff_until.pop(group, None)
                else:
                    self._permitted[group] = left

    def unreserve_group(self, pod: Pod) -> None:
        """Called when a gang member fails downstream (Permit timeout,
        bind failure, unreserve): undo its arrival, REJECT every member
        still waiting at Permit (a partial gang must not keep squatting
        on reserved resources for the full permit timeout), and start
        the gang's backoff window."""
        group, _ = pod_group(pod)
        if not group:
            return
        with self._lock:
            left = self._permitted.get(group, 0) - 1
            if left > 0:
                self._permitted[group] = left
            else:
                # zeroed groups drop their counter — failed/deleted-
                # while-pending gangs must not accumulate state forever
                self._permitted.pop(group, None)
            if self.backoff_seconds > 0:
                self._backoff_until[group] = (
                    time.monotonic() + self.backoff_seconds
                )
                # opportunistic prune: expired backoff windows are dead
                # weight (note_member_deleted only covers bound gangs)
                if len(self._backoff_until) > 1024:
                    now = time.monotonic()
                    self._backoff_until = {
                        g: t for g, t in self._backoff_until.items()
                        if t > now
                    }
            members = self._waiting.pop(group, set())
            members.discard(pod.uid)
        if self.handle is None:
            return
        for uid in members:
            wp = self.handle.get_waiting_pod(uid)
            if wp is not None:
                wp.reject(
                    self.NAME,
                    f"gang {group} member {pod.name} failed admission",
                )
