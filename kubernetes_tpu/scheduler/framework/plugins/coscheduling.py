"""Coscheduling (gang scheduling) Permit plugin.

The reference tree has no in-tree equivalent — gang scheduling is the
Permit-phase pattern of the out-of-tree coscheduling plugin, enabled by the
framework's ``RunPermitPlugins``/``WaitOnPermit`` machinery
(``runtime/framework.go:960,1011``; see SURVEY.md section 6). Pods declare a
gang via labels:

    pod-group.scheduling.k8s.io/name: <group>
    pod-group.scheduling.k8s.io/min-available: "<N>"

A pod whose gang hasn't reached N scheduled-or-waiting members Waits at
Permit; when the N-th member arrives, every waiting member is allowed.
BASELINE config #5 exercises this together with spread + fit.
"""

import threading
from typing import Dict, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    UNSCHEDULABLE,
    WAIT,
    PermitPlugin,
    Status,
)

GROUP_NAME_LABEL = "pod-group.scheduling.k8s.io/name"
MIN_AVAILABLE_LABEL = "pod-group.scheduling.k8s.io/min-available"
DEFAULT_WAIT_SECONDS = 60.0


def pod_group(pod: Pod) -> Tuple[str, int]:
    name = pod.metadata.labels.get(GROUP_NAME_LABEL, "")
    try:
        min_available = int(pod.metadata.labels.get(MIN_AVAILABLE_LABEL, "0"))
    except ValueError:
        min_available = 0
    return name, min_available


class Coscheduling(PermitPlugin):
    NAME = "Coscheduling"

    @staticmethod
    def factory(args, handle):
        return Coscheduling(handle, args or {})

    def __init__(self, handle=None, args=None):
        self.handle = handle
        self.wait_seconds = float((args or {}).get("permitWaitSeconds", DEFAULT_WAIT_SECONDS))
        self._lock = threading.Lock()
        self._permitted: Dict[str, int] = {}  # group -> pods at/past Permit

    def permit(self, state, pod: Pod, node_name: str):
        group, min_available = pod_group(pod)
        if not group or min_available <= 1:
            return None, 0.0
        with self._lock:
            self._permitted[group] = self._permitted.get(group, 0) + 1
            arrived = self._permitted[group]
        if arrived >= min_available:
            # release every gang member parked at Permit
            def allow(wp):
                g, _ = pod_group(wp.pod)
                if g == group:
                    wp.allow(self.NAME)

            self.handle.iterate_waiting_pods(allow)
            return None, 0.0
        return Status(WAIT, f"waiting for gang {group}"), self.wait_seconds

    def unreserve_group(self, pod: Pod) -> None:
        """Called when a gang member fails downstream: undo its arrival."""
        group, _ = pod_group(pod)
        if group:
            with self._lock:
                if self._permitted.get(group, 0) > 0:
                    self._permitted[group] -= 1
