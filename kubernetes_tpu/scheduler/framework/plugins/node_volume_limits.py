"""Node volume-count limits (reference ``plugins/nodevolumelimits/`` — 907
LoC across csi.go + non_csi.go): per-node attachable-volume caps for CSI
drivers (from CSINode allocatable) and the in-tree cloud disks.

Limit resolution order mirrors the reference:

- **CSI** (``csi.go``): the per-driver attach limit comes from the
  node's CSINode object (``drivers[].allocatable.count``). Bound PVCs
  resolve their driver through the PV (including in-tree PVs served via
  CSI migration — the PV carries the CSI driver name); UNBOUND PVCs
  resolve through the StorageClass provisioner
  (``getCSIDriverInfoFromSC``) — a pending claim still consumes an
  attach slot on whatever node it lands on, so it must count.
- **In-tree disks** (``non_csi.go``): per-node limit from the node's
  ``attachable-volumes-<kind>`` allocatable resource when the cloud
  provider published one, else the ``KUBE_MAX_PD_VOLS`` env override,
  else the fleet default (EBS 39, GCE PD 16, Azure Disk 16).
"""

import os
from typing import Optional, Set, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo

ERR_REASON = "node(s) exceed max volume count"

DEFAULT_EBS_LIMIT = 39
DEFAULT_GCE_PD_LIMIT = 16
DEFAULT_AZURE_LIMIT = 16


def pod_csi_volumes(client, pod: Pod) -> Set[Tuple[str, str]]:
    """(driver, volume-key) pairs the pod would attach. Bound PVCs
    resolve via the PV (csi.go getCSIDriverInfo); unbound PVCs via
    the StorageClass provisioner (getCSIDriverInfoFromSC) — keyed by
    the claim itself, since no PV exists yet. Shared single source of
    truth between this filter and the batch encoder's attach-limit
    resource columns (``ops/encode.py``) — the two must count the same
    volumes or the device path diverges from the host filter."""
    out = set()
    for vol in pod.spec.volumes:
        if not vol.persistent_volume_claim:
            continue
        pvc = client.get_pvc(pod.namespace, vol.persistent_volume_claim)
        if pvc is None:
            continue
        if pvc.volume_name:
            pv = client.get_pv(pvc.volume_name)
            if pv is None:
                continue
            driver = getattr(pv, "csi_driver", None)
            if driver:
                out.add((driver, pv.name))
            continue
        # unbound claim: the provisioner that WILL serve it defines
        # which driver's attach budget it consumes
        sc_name = pvc.storage_class_name
        if not sc_name:
            continue
        sc = client.get_storage_class(sc_name)
        if sc is None or not sc.provisioner:
            continue
        out.add((sc.provisioner, f"{pod.namespace}/{pvc.name}"))
    return out


class CSILimits(FilterPlugin):
    NAME = "NodeVolumeLimits"

    @staticmethod
    def factory(args, handle):
        return CSILimits(handle)

    def __init__(self, handle=None):
        self.handle = handle

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        client = self.handle.client
        csi_node = client.get_csi_node(node_info.node.name)
        if csi_node is None:
            return None
        wanted = self._pod_csi_volumes(client, pod)
        if not wanted:
            return None
        in_use = set()
        for pi in node_info.pods:
            in_use |= self._pod_csi_volumes(client, pi.pod)
        for driver in csi_node.drivers:
            limit = driver.allocatable_count
            if limit is None:
                continue
            new_count = len(
                {v for d, v in (in_use | wanted) if d == driver.name}
            )
            if new_count > limit:
                return Status(UNSCHEDULABLE, ERR_REASON)
        return None

    def _pod_csi_volumes(self, client, pod: Pod) -> Set[Tuple[str, str]]:
        return pod_csi_volumes(client, pod)


class _InTreeLimits(FilterPlugin):
    """Shared logic for the in-tree cloud-disk limit filters
    (non_csi.go): limit = node allocatable attachable-volumes resource,
    else KUBE_MAX_PD_VOLS, else the per-cloud default."""

    volume_attr = ""
    # reference volumeutil.<kind>VolumeLimitKey, published by the cloud
    # provider in node.status.allocatable
    allocatable_key = ""
    default_limit = 0

    def __init__(self, handle=None):
        self.handle = handle

    def _node_limit(self, node_info: NodeInfo) -> int:
        node = node_info.node
        if node is not None:
            qty = node.status.allocatable.get(self.allocatable_key)
            if qty is not None:
                return int(qty.value())
        env = os.environ.get("KUBE_MAX_PD_VOLS")
        if env:
            try:
                return int(env)
            except ValueError:
                pass
        return self.default_limit

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        wanted = {
            getattr(v, self.volume_attr)
            for v in pod.spec.volumes
            if getattr(v, self.volume_attr)
        }
        if not wanted:
            return None
        in_use = {
            getattr(v, self.volume_attr)
            for pi in node_info.pods
            for v in pi.pod.spec.volumes
            if getattr(v, self.volume_attr)
        }
        if len(in_use | wanted) > self._node_limit(node_info):
            return Status(UNSCHEDULABLE, ERR_REASON)
        return None


class EBSLimits(_InTreeLimits):
    NAME = "EBSLimits"
    volume_attr = "aws_elastic_block_store"
    allocatable_key = "attachable-volumes-aws-ebs"
    default_limit = DEFAULT_EBS_LIMIT

    @staticmethod
    def factory(args, handle):
        return EBSLimits(handle)


class GCEPDLimits(_InTreeLimits):
    NAME = "GCEPDLimits"
    volume_attr = "gce_persistent_disk"
    allocatable_key = "attachable-volumes-gce-pd"
    default_limit = DEFAULT_GCE_PD_LIMIT

    @staticmethod
    def factory(args, handle):
        return GCEPDLimits(handle)


class AzureDiskLimits(_InTreeLimits):
    NAME = "AzureDiskLimits"
    volume_attr = "azure_disk"
    allocatable_key = "attachable-volumes-azure-disk"
    default_limit = DEFAULT_AZURE_LIMIT

    @staticmethod
    def factory(args, handle):
        return AzureDiskLimits(handle)
