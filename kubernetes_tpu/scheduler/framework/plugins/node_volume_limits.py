"""Node volume-count limits (reference ``plugins/nodevolumelimits/`` — 907
LoC across csi.go + non_csi.go): per-node attachable-volume caps for CSI
drivers (from CSINode allocatable) and the in-tree cloud disks (EBS 39,
GCE PD 16, Azure Disk 16)."""

from typing import Optional, Set, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo

ERR_REASON = "node(s) exceed max volume count"

DEFAULT_EBS_LIMIT = 39
DEFAULT_GCE_PD_LIMIT = 16
DEFAULT_AZURE_LIMIT = 16


class CSILimits(FilterPlugin):
    NAME = "NodeVolumeLimits"

    @staticmethod
    def factory(args, handle):
        return CSILimits(handle)

    def __init__(self, handle=None):
        self.handle = handle

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        client = self.handle.client
        csi_node = client.get_csi_node(node_info.node.name)
        if csi_node is None:
            return None
        wanted = self._pod_csi_volumes(client, pod)
        if not wanted:
            return None
        in_use = set()
        for pi in node_info.pods:
            in_use |= self._pod_csi_volumes(client, pi.pod)
        for driver in csi_node.drivers:
            limit = driver.allocatable_count
            if limit is None:
                continue
            new_count = len(
                {v for d, v in (in_use | wanted) if d == driver.name}
            )
            if new_count > limit:
                return Status(UNSCHEDULABLE, ERR_REASON)
        return None

    def _pod_csi_volumes(self, client, pod: Pod) -> Set[Tuple[str, str]]:
        out = set()
        for vol in pod.spec.volumes:
            if not vol.persistent_volume_claim:
                continue
            pvc = client.get_pvc(pod.namespace, vol.persistent_volume_claim)
            if pvc is None or not pvc.volume_name:
                continue
            pv = client.get_pv(pvc.volume_name)
            if pv is None:
                continue
            driver = getattr(pv, "csi_driver", None)
            if driver:
                out.add((driver, pv.name))
        return out


class _InTreeLimits(FilterPlugin):
    """Shared logic for the in-tree cloud-disk limit filters."""

    volume_attr = ""
    default_limit = 0

    def __init__(self, handle=None):
        self.handle = handle

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        wanted = {
            getattr(v, self.volume_attr)
            for v in pod.spec.volumes
            if getattr(v, self.volume_attr)
        }
        if not wanted:
            return None
        in_use = {
            getattr(v, self.volume_attr)
            for pi in node_info.pods
            for v in pi.pod.spec.volumes
            if getattr(v, self.volume_attr)
        }
        if len(in_use | wanted) > self.default_limit:
            return Status(UNSCHEDULABLE, ERR_REASON)
        return None


class EBSLimits(_InTreeLimits):
    NAME = "EBSLimits"
    volume_attr = "aws_elastic_block_store"
    default_limit = DEFAULT_EBS_LIMIT

    @staticmethod
    def factory(args, handle):
        return EBSLimits(handle)


class GCEPDLimits(_InTreeLimits):
    NAME = "GCEPDLimits"
    volume_attr = "gce_persistent_disk"
    default_limit = DEFAULT_GCE_PD_LIMIT

    @staticmethod
    def factory(args, handle):
        return GCEPDLimits(handle)


class AzureDiskLimits(_InTreeLimits):
    NAME = "AzureDiskLimits"
    volume_attr = "gce_persistent_disk"  # azure disk volumes unsupported in the
    default_limit = DEFAULT_AZURE_LIMIT  # object model; counts like GCE PD

    @staticmethod
    def factory(args, handle):
        return AzureDiskLimits(handle)
