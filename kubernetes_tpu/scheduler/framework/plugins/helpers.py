"""Shared plugin helpers (reference ``plugins/helper/``): node-selector and
node-affinity matching, and the default min-max score normalizer."""

from __future__ import annotations

from typing import List, Optional

from kubernetes_tpu.api.types import Node, NodeSelector, NodeSelectorTerm, Pod
from kubernetes_tpu.scheduler.framework.interface import MAX_NODE_SCORE, NodeScore


def node_selector_term_matches(term: NodeSelectorTerm, node: Node) -> bool:
    """A term with no expressions/fields matches nothing (reference
    v1helper.MatchNodeSelectorTerms)."""
    if not term.match_expressions and not term.match_fields:
        return False
    for req in term.match_expressions:
        if not req.to_requirement().matches(node.metadata.labels):
            return False
    for req in term.match_fields:
        # the only supported field is metadata.name
        if req.key != "metadata.name":
            return False
        if not req.to_requirement().matches({"metadata.name": node.name}):
            return False
    return True


def node_matches_node_selector(node: Node, selector: Optional[NodeSelector]) -> bool:
    """ORed terms; nil selector matches everything, empty terms match nothing."""
    if selector is None:
        return True
    return any(
        node_selector_term_matches(t, node) for t in selector.node_selector_terms
    )


def pod_matches_node_selector_and_affinity(pod: Pod, node: Node) -> bool:
    """Reference PodMatchesNodeSelectorAndAffinityTerms: both the simple
    nodeSelector map and requiredDuringScheduling node affinity must hold."""
    if pod.spec.node_selector:
        for k, v in pod.spec.node_selector.items():
            if node.metadata.labels.get(k) != v:
                return False
    aff = pod.spec.affinity
    if (
        aff is not None
        and aff.node_affinity is not None
        and aff.node_affinity.required_during_scheduling_ignored_during_execution
        is not None
    ):
        terms = (
            aff.node_affinity.required_during_scheduling_ignored_during_execution
        )
        if not node_matches_node_selector(node, terms):
            return False
    return True


def default_normalize_score(
    max_priority: int, reverse: bool, scores: List[NodeScore]
) -> None:
    """Scale raw scores into [0, max_priority] by the max; optionally
    reverse (reference helper.DefaultNormalizeScore)."""
    max_count = max((s.score for s in scores), default=0)
    if max_count == 0:
        if reverse:
            for s in scores:
                s.score = max_priority
        return
    for s in scores:
        score = s.score * max_priority // max_count
        s.score = max_priority - score if reverse else score
