"""In-tree plugins (reference ``pkg/scheduler/framework/plugins/`` — the 22
predicates/priorities inventoried in SURVEY.md section 2.4) plus this
framework's own additions (coscheduling gang Permit plugin, TPU batch
integration). ``new_in_tree_registry`` mirrors ``plugins/registry.go``; out-
of-tree plugins merge via ``Registry.merge`` (the ``WithFrameworkOutOfTreeRegistry``
mechanism the TPU plugin uses)."""

from kubernetes_tpu.scheduler.framework.runtime import Registry


def new_in_tree_registry() -> Registry:
    from kubernetes_tpu.scheduler.framework.plugins import (
        default_binder,
        default_preemption,
        image_locality,
        interpod_affinity,
        node_affinity,
        node_label,
        node_name,
        node_ports,
        node_prefer_avoid_pods,
        node_resources,
        node_unschedulable,
        node_volume_limits,
        pod_topology_spread,
        queue_sort,
        selector_spread,
        service_affinity,
        taint_toleration,
        volume_binding,
        volume_restrictions,
        volume_zone,
        coscheduling,
        mesh_locality,
    )

    r = Registry()
    r.register(queue_sort.PrioritySort.NAME, queue_sort.PrioritySort.factory)
    r.register(node_resources.Fit.NAME, node_resources.Fit.factory)
    r.register(
        node_resources.BalancedAllocation.NAME,
        node_resources.BalancedAllocation.factory,
    )
    r.register(
        node_resources.LeastAllocated.NAME, node_resources.LeastAllocated.factory
    )
    r.register(node_resources.MostAllocated.NAME, node_resources.MostAllocated.factory)
    r.register(
        node_resources.RequestedToCapacityRatio.NAME,
        node_resources.RequestedToCapacityRatio.factory,
    )
    r.register(node_name.NodeName.NAME, node_name.NodeName.factory)
    r.register(node_ports.NodePorts.NAME, node_ports.NodePorts.factory)
    r.register(
        node_unschedulable.NodeUnschedulable.NAME,
        node_unschedulable.NodeUnschedulable.factory,
    )
    r.register(node_affinity.NodeAffinity.NAME, node_affinity.NodeAffinity.factory)
    r.register(node_label.NodeLabel.NAME, node_label.NodeLabel.factory)
    r.register(
        node_prefer_avoid_pods.NodePreferAvoidPods.NAME,
        node_prefer_avoid_pods.NodePreferAvoidPods.factory,
    )
    r.register(
        taint_toleration.TaintToleration.NAME, taint_toleration.TaintToleration.factory
    )
    r.register(
        interpod_affinity.InterPodAffinity.NAME,
        interpod_affinity.InterPodAffinity.factory,
    )
    r.register(
        pod_topology_spread.PodTopologySpread.NAME,
        pod_topology_spread.PodTopologySpread.factory,
    )
    r.register(
        selector_spread.SelectorSpread.NAME, selector_spread.SelectorSpread.factory
    )
    r.register(
        service_affinity.ServiceAffinity.NAME, service_affinity.ServiceAffinity.factory
    )
    r.register(image_locality.ImageLocality.NAME, image_locality.ImageLocality.factory)
    r.register(volume_binding.VolumeBinding.NAME, volume_binding.VolumeBinding.factory)
    r.register(
        volume_restrictions.VolumeRestrictions.NAME,
        volume_restrictions.VolumeRestrictions.factory,
    )
    r.register(volume_zone.VolumeZone.NAME, volume_zone.VolumeZone.factory)
    r.register(node_volume_limits.CSILimits.NAME, node_volume_limits.CSILimits.factory)
    r.register(
        node_volume_limits.EBSLimits.NAME, node_volume_limits.EBSLimits.factory
    )
    r.register(
        node_volume_limits.GCEPDLimits.NAME, node_volume_limits.GCEPDLimits.factory
    )
    r.register(
        node_volume_limits.AzureDiskLimits.NAME,
        node_volume_limits.AzureDiskLimits.factory,
    )
    r.register(
        default_preemption.DefaultPreemption.NAME,
        default_preemption.DefaultPreemption.factory,
    )
    r.register(default_binder.DefaultBinder.NAME, default_binder.DefaultBinder.factory)
    r.register(coscheduling.Coscheduling.NAME, coscheduling.Coscheduling.factory)
    r.register(
        coscheduling.CoschedulingSort.NAME,
        coscheduling.CoschedulingSort.factory,
    )
    r.register(
        mesh_locality.MeshLocality.NAME, mesh_locality.MeshLocality.factory
    )
    return r
