"""TaintToleration filter + PreferNoSchedule scoring (reference
``plugins/tainttoleration/taint_toleration.go``)."""

from typing import List, Optional, Tuple

from kubernetes_tpu.api.types import NO_SCHEDULE, NO_EXECUTE, PREFER_NO_SCHEDULE, Pod
from kubernetes_tpu.scheduler.framework.interface import (
    MAX_NODE_SCORE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    NodeScore,
    PreScorePlugin,
    ScoreExtensions,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.scheduler.framework.plugins.helpers import default_normalize_score
from kubernetes_tpu.scheduler.types import NodeInfo

PRE_SCORE_STATE_KEY = "PreScoreTaintToleration"


def find_untolerated_taint(taints, tolerations, effect_filter):
    for taint in taints:
        if not effect_filter(taint):
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return taint
    return None


class TaintToleration(FilterPlugin, PreScorePlugin, ScorePlugin):
    NAME = "TaintToleration"

    @staticmethod
    def factory(args, handle):
        return TaintToleration(handle)

    def __init__(self, handle=None):
        self.handle = handle

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        taint = find_untolerated_taint(
            node_info.node.spec.taints,
            pod.spec.tolerations,
            lambda t: t.effect in (NO_SCHEDULE, NO_EXECUTE),
        )
        if taint is not None:
            return Status(
                UNSCHEDULABLE_AND_UNRESOLVABLE,
                f"node(s) had taint {{{taint.key}: {taint.value}}}, "
                "that the pod didn't tolerate",
            )
        return None

    def pre_score(self, state, pod: Pod, nodes: List) -> Optional[Status]:
        # only PreferNoSchedule-effect tolerations matter for scoring
        tolerations = [
            t
            for t in pod.spec.tolerations
            if t.effect in ("", PREFER_NO_SCHEDULE)
        ]
        state.write(PRE_SCORE_STATE_KEY, tolerations)
        return None

    def score(self, state, pod: Pod, node_name: str) -> Tuple[int, Optional[Status]]:
        node_info = self.handle.snapshot().get(node_name)
        if node_info is None or node_info.node is None:
            return 0, Status(1, f"node {node_name} not found")
        try:
            tolerations = state.read(PRE_SCORE_STATE_KEY)
        except KeyError:
            tolerations = []
        count = 0
        for taint in node_info.node.spec.taints:
            if taint.effect != PREFER_NO_SCHEDULE:
                continue
            if not any(t.tolerates(taint) for t in tolerations):
                count += 1
        return count, None

    def score_extensions(self):
        return _Normalize()


class _Normalize(ScoreExtensions):
    def normalize_score(self, state, pod, scores: List[NodeScore]):
        # more intolerable PreferNoSchedule taints -> lower score
        default_normalize_score(MAX_NODE_SCORE, True, scores)
        return None
