"""SelectorSpread score (legacy default spreading; reference
``plugins/selectorspread/selector_spread.go``): spreads pods belonging to
the same Service/ReplicationController/ReplicaSet/StatefulSet across nodes
and zones (zone weighted 2/3)."""

from typing import List, Optional, Tuple

from kubernetes_tpu.api.labels import Selector
from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    MAX_NODE_SCORE,
    NodeScore,
    PreScorePlugin,
    ScoreExtensions,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.scheduler.node_tree import get_zone_key

PRE_SCORE_STATE_KEY = "PreScoreSelectorSpread"
ZONE_WEIGHTING = 2.0 / 3.0


def get_pod_selectors(client, pod: Pod) -> List[Selector]:
    """Selectors of every controller-ish object selecting this pod
    (reference helper.DefaultSelector)."""
    selectors: List[Selector] = []
    ns = pod.namespace
    labels = pod.metadata.labels
    for svc in client.list_services(ns):
        sel = Selector.from_map(svc.selector)
        if not sel.is_empty() and sel.matches(labels):
            selectors.append(sel)
    for rc in client.list_replication_controllers(ns):
        sel = Selector.from_map(rc.selector)
        if not sel.is_empty() and sel.matches(labels):
            selectors.append(sel)
    for rs in client.list_replica_sets(ns):
        if rs.selector is not None:
            sel = rs.selector.to_selector()
            if sel.matches(labels):
                selectors.append(sel)
    for ss in client.list_stateful_sets(ns):
        if ss.selector is not None:
            sel = ss.selector.to_selector()
            if sel.matches(labels):
                selectors.append(sel)
    return selectors


class SelectorSpread(PreScorePlugin, ScorePlugin):
    NAME = "SelectorSpread"

    @staticmethod
    def factory(args, handle):
        return SelectorSpread(handle)

    def __init__(self, handle=None):
        self.handle = handle

    def pre_score(self, state, pod: Pod, nodes: List) -> Optional[Status]:
        selectors = get_pod_selectors(self.handle.client, pod)
        state.write(PRE_SCORE_STATE_KEY, selectors)
        return None

    def score(self, state, pod: Pod, node_name: str) -> Tuple[int, Optional[Status]]:
        node_info = self.handle.snapshot().get(node_name)
        if node_info is None or node_info.node is None:
            return 0, Status(1, f"node {node_name} not found")
        try:
            selectors = state.read(PRE_SCORE_STATE_KEY)
        except KeyError:
            selectors = []
        if not selectors:
            return 0, None
        count = sum(
            1
            for pi in node_info.pods
            if pi.pod.namespace == pod.namespace
            and pi.pod.metadata.deletion_timestamp is None
            and any(sel.matches(pi.pod.metadata.labels) for sel in selectors)
        )
        return count, None

    def score_extensions(self):
        return _Normalize(self.handle)


class _Normalize(ScoreExtensions):
    def __init__(self, handle):
        self.handle = handle

    def normalize_score(self, state, pod, scores: List[NodeScore]):
        """Invert raw match counts, blending per-node and per-zone counts
        (selector_spread.go NormalizeScore; zone weighted 2/3)."""
        snapshot = self.handle.snapshot()
        zone_counts = {}
        have_zones = False
        for s in scores:
            ni = snapshot.get(s.name)
            if ni is None or ni.node is None:
                continue
            zone = get_zone_key(ni.node)
            if zone:
                have_zones = True
                zone_counts[zone] = zone_counts.get(zone, 0) + s.score
        max_count = max((s.score for s in scores), default=0)
        max_zone = max(zone_counts.values(), default=0)
        for s in scores:
            # fewer same-selector pods -> higher score
            score = (
                MAX_NODE_SCORE * (max_count - s.score) / max_count
                if max_count > 0
                else MAX_NODE_SCORE
            )
            if have_zones:
                ni = snapshot.get(s.name)
                zone = get_zone_key(ni.node) if ni and ni.node else ""
                zone_score = MAX_NODE_SCORE
                if zone and max_zone > 0:
                    zone_score = (
                        MAX_NODE_SCORE * (max_zone - zone_counts.get(zone, 0)) / max_zone
                    )
                score = score * (1 - ZONE_WEIGHTING) + ZONE_WEIGHTING * zone_score
            s.score = int(score)
        return None
