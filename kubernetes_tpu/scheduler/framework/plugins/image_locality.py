"""ImageLocality score (reference
``plugins/imagelocality/image_locality.go``): prefers nodes that already
hold the pod's container images, scaled by image size and how widely the
image is spread across nodes."""

from typing import Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    MAX_NODE_SCORE,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo

MB = 1024 * 1024
MIN_THRESHOLD = 23 * MB
MAX_CONTAINER_THRESHOLD = 1000 * MB


class ImageLocality(ScorePlugin):
    NAME = "ImageLocality"

    @staticmethod
    def factory(args, handle):
        return ImageLocality(handle)

    def __init__(self, handle=None):
        self.handle = handle

    def score(self, state, pod: Pod, node_name: str) -> Tuple[int, Optional[Status]]:
        snapshot = self.handle.snapshot()
        node_info = snapshot.get(node_name)
        if node_info is None or node_info.node is None:
            return 0, Status(1, f"node {node_name} not found")
        total_nodes = snapshot.num_nodes()
        if total_nodes == 0:
            return 0, None
        sum_scores = _sum_image_scores(node_info, pod, total_nodes)
        max_threshold = MAX_CONTAINER_THRESHOLD * max(len(pod.spec.containers), 1)
        score = int(
            MAX_NODE_SCORE
            * _clamp01((sum_scores - MIN_THRESHOLD) / (max_threshold - MIN_THRESHOLD))
        )
        return score, None


def _sum_image_scores(node_info: NodeInfo, pod: Pod, total_nodes: int) -> float:
    total = 0.0
    for container in pod.spec.containers:
        state = _lookup_image(node_info, container.image)
        if state is not None:
            # spread ratio dampens hotspots on rarely-pulled images
            total += state.size * (state.num_nodes / total_nodes)
    return total


def _lookup_image(node_info: NodeInfo, image: str):
    if not image:
        return None
    candidates = [image]
    if ":" not in image.rsplit("/", 1)[-1]:
        candidates.append(image + ":latest")
    for name in candidates:
        if name in node_info.image_states:
            return node_info.image_states[name]
    return None


def _clamp01(x: float) -> float:
    return 0.0 if x < 0 else (1.0 if x > 1 else x)
