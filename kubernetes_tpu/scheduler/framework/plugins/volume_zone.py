"""VolumeZone filter (reference ``plugins/volumezone/volume_zone.go``): a
bound PV carrying zone/region labels constrains the pod to nodes in that
zone/region."""

from typing import Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo

ERR_REASON_CONFLICT = "node(s) had no available volume zone"

TOPOLOGY_LABELS = (
    "topology.kubernetes.io/zone",
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
)


class VolumeZone(FilterPlugin):
    NAME = "VolumeZone"

    @staticmethod
    def factory(args, handle):
        return VolumeZone(handle)

    def __init__(self, handle=None):
        self.handle = handle

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        client = self.handle.client
        node_labels = node_info.node.metadata.labels
        for vol in pod.spec.volumes:
            if not vol.persistent_volume_claim:
                continue
            pvc = client.get_pvc(pod.namespace, vol.persistent_volume_claim)
            if pvc is None or not pvc.volume_name:
                continue
            pv = client.get_pv(pvc.volume_name)
            if pv is None:
                continue
            for label in TOPOLOGY_LABELS:
                pv_value = pv.metadata.labels.get(label)
                if pv_value is None:
                    continue
                # multi-zone PVs use __ separators (volume helper zones set)
                allowed = set(pv_value.split("__"))
                if node_labels.get(label) not in allowed:
                    return Status(UNSCHEDULABLE, ERR_REASON_CONFLICT)
        return None
