"""MeshLocality: device-mesh-adjacency scoring for multi-chip gangs.

PAPERS.md's Pathways case names the workload that matters for ML
control planes: gang placement on MESH-ADJACENT accelerators — a
multi-chip program pays inter-chip latency proportional to how far
apart its hosts sit on the device mesh, so the scheduler should pull a
gang's members onto neighboring mesh coordinates, not merely onto any
N feasible nodes.

Topology label scheme (nodes):

    ktpu.io/mesh-x: "<col>"
    ktpu.io/mesh-y: "<row>"

— the node's coordinate on the accelerator mesh (the harness stamps
these from the node index over a cols×rows grid; on real fleets they
come from the fabric inventory). Pods opt in with:

    ktpu.io/mesh-block: "<block-name>"

(normally the pod's gang name). Every member of a block shares a
deterministic ANCHOR coordinate — crc32(block) hashed onto the grid —
and a node scores by Manhattan closeness to that anchor:

    score = MAX_NODE_SCORE / (1 + d(node, anchor))

Strictly decreasing in distance, so the argmax packs members onto the
anchor's neighborhood; capacity pressure spills them to the NEXT
nearest ring rather than across the mesh. Unlabeled pods and unlabeled
nodes score 0 — the plugin is free for every existing workload.

ONE closeness function feeds BOTH scheduling paths: the serial
framework path via this ScorePlugin (quantized to the framework's
integer score contract, like every in-tree Score plugin), the batch
path via ``BatchEncoder._compute_static`` at full float precision
(which also folds ``profile_component`` into the static-profile key
so two gangs with different anchors never share a score column). The
paths share the function, not bit-equal totals — batch score
composition is its own float formulation throughout (image locality,
preferred-affinity weights), so no serial≡batch score-equality
contract exists to preserve here.

``configure(enabled=False)`` is the adjacency-blind baseline arm of
the replay gang family's A/B — scoring vanishes, gang semantics stay.
"""

from __future__ import annotations

import zlib
from typing import Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    MAX_NODE_SCORE,
    ScorePlugin,
    Status,
)

MESH_X_LABEL = "ktpu.io/mesh-x"
MESH_Y_LABEL = "ktpu.io/mesh-y"
MESH_BLOCK_LABEL = "ktpu.io/mesh-block"

# adjacency-blind switch (the gang family's baseline arm); module-level
# because the batch encoder calls the free function, not the plugin
_ENABLED = True


def configure(enabled: bool) -> None:
    global _ENABLED
    _ENABLED = bool(enabled)


def enabled() -> bool:
    return _ENABLED


def mesh_node_labels(index: int, cols: int, rows: int = 0) -> dict:
    """The label scheme for node ``index`` on a cols×rows grid (rows
    defaults to unbounded — index//cols). Shared by the scenario
    harness, the chaos cells, and the tests."""
    x, y = index % cols, index // cols
    if rows:
        y %= rows
    return {MESH_X_LABEL: str(x), MESH_Y_LABEL: str(y)}


def node_coord(node) -> Optional[Tuple[int, int]]:
    labels = node.metadata.labels
    sx, sy = labels.get(MESH_X_LABEL), labels.get(MESH_Y_LABEL)
    if sx is None or sy is None:
        return None
    try:
        return int(sx), int(sy)
    except ValueError:
        return None


def block_anchor(block: str, cols: int, rows: int) -> Tuple[int, int]:
    """Deterministic anchor coordinate for a mesh block: crc32 of the
    block name hashed onto the grid. Every scheduler replica — and the
    batch encoder — derives the identical anchor with no coordination."""
    h = zlib.crc32(block.encode())
    return (h % max(cols, 1), (h // max(cols, 1)) % max(rows, 1))


def mesh_block(pod: Pod) -> str:
    return pod.metadata.labels.get(MESH_BLOCK_LABEL, "")


def profile_component(pod: Pod) -> tuple:
    """Static-profile-key component: pods of different blocks must NOT
    share a static score column (their anchors differ). Empty for
    unlabeled pods, so every existing workload's key is unchanged."""
    block = mesh_block(pod)
    return ("mesh", block) if block else ()


def _grid_extent(snapshot_nodes) -> Tuple[int, int]:
    """Grid extent from the labeled nodes actually present (anchors
    must land on real coordinates). Cached per call site — cheap:
    O(nodes) over labels only."""
    cols = rows = 0
    for node in snapshot_nodes:
        c = node_coord(node)
        if c is not None:
            cols = max(cols, c[0] + 1)
            rows = max(rows, c[1] + 1)
    return cols, rows


def profile_scorer(pod: Pod, all_nodes):
    """The shared closeness function, hoisted per pod-profile: returns
    None when the pod doesn't participate (no block label, plugin
    disabled, or no labeled grid present), else ``fn(node) -> float``
    computing MAX/(1+manhattan distance to the block anchor). The batch
    encoder calls this once per static profile and sweeps nodes; the
    serial plugin caches one scorer per (pod, snapshot) — both paths
    evaluate the IDENTICAL function (differential exactness).
    ``all_nodes`` must be the FULL candidate node-object list (the
    anchor grid extent) even when the caller sweeps only a shard."""
    if not _ENABLED:
        return None
    block = mesh_block(pod)
    if not block:
        return None
    cols, rows = _grid_extent(all_nodes)
    if not cols or not rows:
        return None
    ax, ay = block_anchor(block, cols, rows)

    def score(node) -> float:
        c = node_coord(node)
        if c is None:
            return 0.0
        d = abs(c[0] - ax) + abs(c[1] - ay)
        return float(MAX_NODE_SCORE) / (1.0 + d)

    return score


class MeshLocality(ScorePlugin):
    """The serial-path face of the shared closeness function."""

    NAME = "MeshLocality"

    @staticmethod
    def factory(args, handle):
        return MeshLocality(handle)

    def __init__(self, handle=None):
        self.handle = handle
        # (pod uid, snapshot) -> scorer: the framework scores one pod
        # against many nodes per cycle; rebuild the anchor/extent once
        # per (pod, snapshot), not once per node. The memo holds a
        # STRONG reference to the snapshot and compares by identity —
        # an id()-keyed memo could hand a retried pod a scorer built
        # from a freed snapshot whose address got reused
        self._memo_uid = None
        self._memo_snap = None
        self._memo_fn = None

    def score(self, state, pod: Pod, node_name: str
              ) -> Tuple[int, Optional[Status]]:
        if not _ENABLED or not mesh_block(pod):
            return 0, None
        snapshot = self.handle.snapshot()
        ni = snapshot.get(node_name)
        if ni is None or ni.node is None:
            return 0, Status(1, f"node {node_name} not found")
        if pod.uid != self._memo_uid or snapshot is not self._memo_snap:
            nodes = [i.node for i in snapshot.list()
                     if i.node is not None]
            self._memo_fn = profile_scorer(pod, nodes)
            self._memo_uid = pod.uid
            self._memo_snap = snapshot
        if self._memo_fn is None:
            return 0, None
        return int(round(self._memo_fn(ni.node))), None
