"""NodeLabel filter+score (legacy; reference
``plugins/nodelabel/node_label.go``): presence/absence requirements and
preferences over node labels, configured via args."""

from typing import Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    MAX_NODE_SCORE,
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo

ERR_REASON_PRESENCE_VIOLATED = "node(s) didn't have the requested labels"


class NodeLabel(FilterPlugin, ScorePlugin):
    NAME = "NodeLabel"

    @staticmethod
    def factory(args, handle):
        return NodeLabel(handle, args or {})

    def __init__(self, handle=None, args=None):
        args = args or {}
        self.handle = handle
        self.present_labels = list(args.get("presentLabels") or [])
        self.absent_labels = list(args.get("absentLabels") or [])
        self.present_labels_preference = list(
            args.get("presentLabelsPreference") or []
        )
        self.absent_labels_preference = list(args.get("absentLabelsPreference") or [])

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        labels = node_info.node.metadata.labels
        ok = all(l in labels for l in self.present_labels) and all(
            l not in labels for l in self.absent_labels
        )
        if not ok:
            return Status(UNSCHEDULABLE, ERR_REASON_PRESENCE_VIOLATED)
        return None

    def score(self, state, pod: Pod, node_name: str) -> Tuple[int, Optional[Status]]:
        node_info = self.handle.snapshot().get(node_name)
        if node_info is None or node_info.node is None:
            return 0, Status(1, f"node {node_name} not found")
        labels = node_info.node.metadata.labels
        score = 0
        total = len(self.present_labels_preference) + len(self.absent_labels_preference)
        if total == 0:
            return 0, None
        for l in self.present_labels_preference:
            if l in labels:
                score += MAX_NODE_SCORE
        for l in self.absent_labels_preference:
            if l not in labels:
                score += MAX_NODE_SCORE
        return score // total, None
