"""NodePorts PreFilter+Filter (reference ``plugins/nodeports/node_ports.go``):
host-port conflicts against ``NodeInfo.used_ports``."""

from typing import List, Optional

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    UNSCHEDULABLE,
    FilterPlugin,
    PreFilterPlugin,
    Status,
)
from kubernetes_tpu.scheduler.types import NodeInfo, pod_host_ports, ports_conflict

PRE_FILTER_STATE_KEY = "PreFilterNodePorts"
ERR_REASON = "node(s) didn't have free ports for the requested pod ports"


class NodePorts(PreFilterPlugin, FilterPlugin):
    NAME = "NodePorts"

    @staticmethod
    def factory(args, handle):
        return NodePorts()

    def pre_filter(self, state, pod: Pod) -> Optional[Status]:
        state.write(PRE_FILTER_STATE_KEY, pod_host_ports(pod))
        return None

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        try:
            wanted: List = state.read(PRE_FILTER_STATE_KEY)
        except KeyError:
            wanted = pod_host_ports(pod)
        if ports_conflict(node_info.used_ports, wanted):
            return Status(UNSCHEDULABLE, ERR_REASON)
        return None
