"""VolumeBinding (reference ``plugins/volumebinding/volume_binding.go`` +
``pkg/controller/volume/scheduling`` SchedulerVolumeBinder): the stateful
plugin spanning PreFilter+Filter+Reserve+PreBind+Unreserve.

Semantics carried over:
- bound PVCs: the PV's node affinity must admit the node;
- unbound PVCs with an Immediate storage class: unschedulable
  ("pod has unbound immediate PersistentVolumeClaims");
- unbound PVCs with WaitForFirstConsumer: try to match an available PV
  (capacity/class/access-modes/node-affinity); if none, the class may
  provision → feasible;
- Reserve assumes the PV→PVC match, PreBind commits it through the API,
  Unreserve rolls back.
"""

from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler.framework.interface import (
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    FilterPlugin,
    PreBindPlugin,
    PreFilterPlugin,
    ReservePlugin,
    Status,
)
from kubernetes_tpu.scheduler.framework.plugins.helpers import (
    node_matches_node_selector,
)
from kubernetes_tpu.scheduler.types import NodeInfo

PRE_FILTER_STATE_KEY = "PreFilterVolumeBinding"

ERR_REASON_BIND_CONFLICT = "node(s) didn't find available persistent volumes to bind"
ERR_REASON_NODE_CONFLICT = "node(s) had volume node affinity conflict"
ERR_REASON_UNBOUND_IMMEDIATE = "pod has unbound immediate PersistentVolumeClaims"
ERR_REASON_PVC_NOT_FOUND = "persistentvolumeclaim not found"


class _PodVolumes:
    __slots__ = ("bound_claims", "claims_to_bind", "matches",
                 "candidates", "node_independent", "cached_chosen")

    def __init__(self):
        self.bound_claims = []   # PVCs already bound to a PV
        self.claims_to_bind = []  # WaitForFirstConsumer PVCs needing a PV
        self.matches: Dict[str, Dict[str, str]] = {}  # node -> {pvc key: pv name}
        # per-class candidate PV lists, built ONCE in PreFilter (the
        # reference's volume binder keeps an indexed PV cache; a per-
        # (pod, node) scan of every PV in the cluster is quadratic)
        self.candidates: Dict[str, list] = {}
        # True when no candidate carries node affinity: the match result
        # is identical on every node, so Filter computes it once
        self.node_independent = False
        self.cached_chosen: Optional[Dict[str, str]] = None

    def clone(self):
        c = _PodVolumes()
        c.bound_claims = list(self.bound_claims)
        c.claims_to_bind = list(self.claims_to_bind)
        c.matches = {n: dict(m) for n, m in self.matches.items()}
        c.candidates = {k: list(v) for k, v in self.candidates.items()}
        c.node_independent = self.node_independent
        c.cached_chosen = (
            dict(self.cached_chosen)
            if self.cached_chosen is not None else None
        )
        return c


class VolumeBinding(PreFilterPlugin, FilterPlugin, ReservePlugin, PreBindPlugin):
    NAME = "VolumeBinding"

    @staticmethod
    def factory(args, handle):
        return VolumeBinding(handle)

    def __init__(self, handle=None):
        self.handle = handle
        # pv name -> pvc key assumed during Reserve, per pod uid
        self._assumed: Dict[str, Dict[str, str]] = {}

    # ------------------------------------------------------------------
    def pre_filter(self, state, pod: Pod) -> Optional[Status]:
        client = self.handle.client
        pv = _PodVolumes()
        for vol in pod.spec.volumes:
            claim_name = vol.persistent_volume_claim
            if not claim_name:
                continue
            pvc = client.get_pvc(pod.namespace, claim_name)
            if pvc is None:
                return Status(
                    UNSCHEDULABLE_AND_UNRESOLVABLE,
                    f'{ERR_REASON_PVC_NOT_FOUND} "{claim_name}"',
                )
            if pvc.volume_name:
                pv.bound_claims.append(pvc)
                continue
            sc = (
                client.get_storage_class(pvc.storage_class_name)
                if pvc.storage_class_name
                else None
            )
            if sc is not None and sc.volume_binding_mode == "WaitForFirstConsumer":
                pv.claims_to_bind.append(pvc)
            else:
                return Status(
                    UNSCHEDULABLE_AND_UNRESOLVABLE, ERR_REASON_UNBOUND_IMMEDIATE
                )
        if pv.claims_to_bind:
            # class-indexed candidate PVs, one pass over the PV table
            # per CYCLE instead of one per (claim, node)
            classes = {c.storage_class_name or "" for c in pv.claims_to_bind}
            for p in client.list_pvs():
                if p.phase != "Available" or p.claim_ref is not None:
                    continue
                cls = p.storage_class_name
                if cls in classes:
                    pv.candidates.setdefault(cls, []).append(p)
            pv.node_independent = all(
                p.node_affinity is None
                for ps in pv.candidates.values() for p in ps
            )
        state.write(PRE_FILTER_STATE_KEY, pv)
        return None

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Optional[Status]:
        if node_info.node is None:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node not found")
        try:
            pv_state: _PodVolumes = state.read(PRE_FILTER_STATE_KEY)
        except KeyError:
            return None
        node = node_info.node
        client = self.handle.client

        # bound claims: PV node affinity must admit this node
        for pvc in pv_state.bound_claims:
            pv = client.get_pv(pvc.volume_name)
            if pv is None or not node_matches_node_selector(node, pv.node_affinity):
                return Status(UNSCHEDULABLE, ERR_REASON_NODE_CONFLICT)

        # delayed-binding claims: find a matching available PV per claim
        if pv_state.claims_to_bind:
            if pv_state.node_independent and \
                    pv_state.cached_chosen is not None:
                # no candidate carries node affinity: the match from
                # the first filtered node holds for every node
                pv_state.matches[node.name] = pv_state.cached_chosen
                return None
            chosen: Dict[str, str] = {}
            used = set()
            for pvc in pv_state.claims_to_bind:
                match = self._find_matching_pv(pv_state, pvc, node, used)
                if match is not None:
                    chosen[f"{pvc.namespace}/{pvc.name}"] = match.name
                    used.add(match.name)
                else:
                    sc = client.get_storage_class(pvc.storage_class_name)
                    if sc is None or not sc.provisioner:
                        return Status(UNSCHEDULABLE, ERR_REASON_BIND_CONFLICT)
                    # dynamic provisioning will satisfy it on this node
            pv_state.matches[node.name] = chosen
            if pv_state.node_independent:
                pv_state.cached_chosen = chosen
        return None

    @staticmethod
    def _find_matching_pv(pv_state, pvc, node, used):
        request = pvc.requests.get("storage")
        for pv in pv_state.candidates.get(pvc.storage_class_name or "", ()):
            if pv.name in used:
                continue
            if pvc.access_modes and not set(pvc.access_modes) <= set(pv.access_modes):
                continue
            if request is not None:
                cap = pv.capacity.get("storage")
                if cap is None or cap < request:
                    continue
            if not node_matches_node_selector(node, pv.node_affinity):
                continue
            return pv
        return None

    # ------------------------------------------------------------------
    def reserve(self, state, pod: Pod, node_name: str) -> Optional[Status]:
        try:
            pv_state: _PodVolumes = state.read(PRE_FILTER_STATE_KEY)
        except KeyError:
            return None
        chosen = pv_state.matches.get(node_name, {})
        client = self.handle.client
        assumed = {}
        for pvc_key, pv_name in chosen.items():
            client.assume_pv_bound(pv_name, pvc_key)
            assumed[pv_name] = pvc_key
        self._assumed[pod.uid] = assumed
        return None

    def unreserve(self, state, pod: Pod, node_name: str) -> None:
        client = self.handle.client
        for pv_name in self._assumed.pop(pod.uid, {}):
            client.revert_assumed_pv(pv_name)

    def pre_bind(self, state, pod: Pod, node_name: str) -> Optional[Status]:
        client = self.handle.client
        for pv_name, pvc_key in self._assumed.pop(pod.uid, {}).items():
            ns, name = pvc_key.split("/", 1)
            ok = client.bind_pv(pv_name, ns, name)
            if not ok:
                return Status(1, f"binding PV {pv_name} to PVC {pvc_key} failed")
        return None
