"""PrioritySort QueueSort plugin (reference
``plugins/queuesort/priority_sort.go:41-45``): higher ``.spec.priority``
first, earlier queue timestamp as tiebreak."""

from kubernetes_tpu.scheduler.framework.interface import QueueSortPlugin
from kubernetes_tpu.scheduler.types import QueuedPodInfo


class PrioritySort(QueueSortPlugin):
    NAME = "PrioritySort"

    @staticmethod
    def factory(args, handle):
        return PrioritySort()

    def less(self, a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        pa, pb = a.pod.priority(), b.pod.priority()
        if pa != pb:
            return pa > pb
        return a.timestamp < b.timestamp

    @staticmethod
    def sort_key(qpi: QueuedPodInfo) -> tuple:
        """Total-order key equivalent of ``less`` (ascending sort puts
        the queue head first). Enables the queue's bulk C-sorted drain."""
        return (-qpi.pod.priority(), qpi.timestamp)
