"""HTTP scheduler extenders (reference ``pkg/scheduler/core/extender.go``):
the legacy out-of-process webhook protocol — Filter/Prioritize/Bind/
ProcessPreemption over HTTP+JSON, called sequentially after in-tree filters
(generic_scheduler.go:347-398). Kept for capability parity; it is also the
architectural known-bad precedent the TPU batch path improves on
(SURVEY.md section 2.5).

``Extender.implementation`` allows an in-process object implementing the
verbs directly (the reference's fake_extender test pattern); otherwise the
verbs go over HTTP via urllib.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.config.types import Extender as ExtenderConfig
from kubernetes_tpu.scheduler.types import NodeInfo


class ExtenderError(Exception):
    pass


class HTTPExtender:
    def __init__(self, config: ExtenderConfig):
        self.config = config
        self.weight = config.weight

    @property
    def name(self) -> str:
        return self.config.url_prefix or "in-process-extender"

    def is_ignorable(self) -> bool:
        return self.config.ignorable

    def is_interested(self, pod: Pod) -> bool:
        return self.config.is_interested(pod)

    def is_binder(self) -> bool:
        return bool(self.config.bind_verb) or (
            self.config.implementation is not None
            and hasattr(self.config.implementation, "bind")
        )

    def supports_preemption(self) -> bool:
        return bool(self.config.preempt_verb) or (
            self.config.implementation is not None
            and hasattr(self.config.implementation, "process_preemption")
        )

    # ------------------------------------------------------------------
    def _call(self, verb: str, payload: dict) -> dict:
        impl = self.config.implementation
        if impl is not None:
            return getattr(impl, verb)(payload)
        url = f"{self.config.url_prefix.rstrip('/')}/{getattr(self.config, verb + '_verb')}"
        req = urllib.request.Request(
            url,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.config.http_timeout) as resp:
            return json.loads(resp.read().decode())

    # ------------------------------------------------------------------
    def filter(
        self, pod: Pod, nodes: List[NodeInfo]
    ) -> Tuple[List[NodeInfo], Dict[str, str]]:
        """Returns (feasible nodes, failed nodes map name->reason)."""
        if not (self.config.filter_verb or self.config.implementation):
            return nodes, {}
        payload = {
            "pod": _pod_to_dict(pod),
            "nodenames": [ni.node.name for ni in nodes if ni.node is not None],
        }
        result = self._call("filter", payload)
        if result.get("error"):
            raise ExtenderError(result["error"])
        failed = dict(result.get("failedNodes") or {})
        keep = result.get("nodenames")
        if keep is None:
            feasible = [
                ni for ni in nodes
                if ni.node is not None and ni.node.name not in failed
            ]
        else:
            keep_set = set(keep)
            feasible = [
                ni for ni in nodes
                if ni.node is not None and ni.node.name in keep_set
            ]
        return feasible, failed

    def prioritize(
        self, pod: Pod, nodes: List[NodeInfo]
    ) -> Dict[str, float]:
        """Returns node -> weighted score contribution."""
        if not (self.config.prioritize_verb or self.config.implementation):
            return {}
        payload = {
            "pod": _pod_to_dict(pod),
            "nodenames": [ni.node.name for ni in nodes if ni.node is not None],
        }
        result = self._call("prioritize", payload)
        return {
            item["host"]: float(item["score"]) * self.weight
            for item in (result or [])
        } if isinstance(result, list) else {
            h: float(s) * self.weight for h, s in (result or {}).items()
        }

    def bind(self, pod: Pod, node_name: str) -> None:
        result = self._call(
            "bind",
            {"podNamespace": pod.namespace, "podName": pod.name,
             "podUID": pod.uid, "node": node_name},
        )
        if result and result.get("error"):
            raise ExtenderError(result["error"])

    def process_preemption(
        self, pod: Pod, victims_by_node: Dict[str, List[Pod]]
    ) -> Dict[str, List[Pod]]:
        if not self.supports_preemption():
            return victims_by_node
        payload = {
            "pod": _pod_to_dict(pod),
            "nodeNameToVictims": {
                n: [_pod_to_dict(v) for v in vs]
                for n, vs in victims_by_node.items()
            },
        }
        result = self._call("process_preemption", payload)
        if result is None:
            return victims_by_node
        keep = set(result.get("nodeNames", victims_by_node.keys()))
        return {n: vs for n, vs in victims_by_node.items() if n in keep}


def _pod_to_dict(pod: Pod) -> dict:
    return {
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "uid": pod.uid,
            "labels": dict(pod.metadata.labels),
        }
    }
