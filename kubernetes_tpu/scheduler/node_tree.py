"""Zone-aware node tree (reference ``internal/cache/node_tree.go:32-36``).

Maintains zone → [node names] and produces a zone-interleaved ordering so a
snapshot's node list spreads consecutive scheduling attempts across zones.
"""

from __future__ import annotations

from typing import Dict, List

from kubernetes_tpu.api.types import Node

ZONE_LABELS = (
    "topology.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/zone",
)
REGION_LABELS = (
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/region",
)


def get_zone_key(node: Node) -> str:
    region = zone = ""
    for l in REGION_LABELS:
        if l in node.metadata.labels:
            region = node.metadata.labels[l]
            break
    for l in ZONE_LABELS:
        if l in node.metadata.labels:
            zone = node.metadata.labels[l]
            break
    if not region and not zone:
        return ""
    return f"{region}:\x00:{zone}"


class NodeTree:
    def __init__(self):
        self._tree: Dict[str, List[str]] = {}
        self._zones: List[str] = []
        self.num_nodes = 0

    def add_node(self, node: Node) -> None:
        zone = get_zone_key(node)
        if zone not in self._tree:
            self._tree[zone] = []
            self._zones.append(zone)
        if node.name in self._tree[zone]:
            return
        self._tree[zone].append(node.name)
        self.num_nodes += 1

    def remove_node(self, node: Node) -> bool:
        zone = get_zone_key(node)
        names = self._tree.get(zone)
        if names and node.name in names:
            names.remove(node.name)
            if not names:
                del self._tree[zone]
                self._zones.remove(zone)
            self.num_nodes -= 1
            return True
        return False

    def update_node(self, old: Node, new: Node) -> None:
        if get_zone_key(old) == get_zone_key(new):
            return
        self.remove_node(old)
        self.add_node(new)

    def list(self) -> List[str]:
        """Round-robin across zones (reference node_tree list ordering)."""
        out: List[str] = []
        idx = [0] * len(self._zones)
        remaining = self.num_nodes
        while remaining > 0:
            for zi, zone in enumerate(self._zones):
                names = self._tree.get(zone, ())
                if idx[zi] < len(names):
                    out.append(names[idx[zi]])
                    idx[zi] += 1
                    remaining -= 1
        return out
