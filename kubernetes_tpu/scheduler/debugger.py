"""Cache debugger: state dumps + cache-vs-truth comparison.

Behavioral equivalent of the reference's scheduler cache debugger
(``pkg/scheduler/internal/cache/debugger/debugger.go:57`` wired to
SIGUSR2 in ``factory.go:160-166``): on demand (or on signal), dump the
cache and queue contents for post-mortem (``dumper.go``), and compare the
scheduler's in-memory cache against the store's ground truth
(``comparer.go``) — the runtime consistency checker that catches cache
drift bugs the type system can't.
"""

from __future__ import annotations

import logging
import signal
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_logger = logging.getLogger(__name__)


@dataclass
class ComparisonResult:
    """Differences between cache and the authoritative store."""

    missing_nodes: List[str] = field(default_factory=list)   # in store, not cache
    redundant_nodes: List[str] = field(default_factory=list)  # in cache, not store
    missing_pods: List[str] = field(default_factory=list)
    redundant_pods: List[str] = field(default_factory=list)

    @property
    def consistent(self) -> bool:
        return not (
            self.missing_nodes or self.redundant_nodes
            or self.missing_pods or self.redundant_pods
        )


class CacheDebugger:
    def __init__(self, store, cache, queue):
        self.store = store
        self.cache = cache
        self.queue = queue

    # -- dumper (debugger/dumper.go) -----------------------------------
    def dump(self) -> Dict:
        """Snapshot of cache nodes/pods + queue contents, log-friendly."""
        cached = self.cache.dump()
        nodes = {}
        for name, info in cached["nodes"].items():
            nodes[name] = {
                "pods": [p.pod.full_name() for p in info.pods],
                "requested_milli_cpu": info.requested.milli_cpu,
                "requested_memory": info.requested.memory,
                "generation": info.generation,
            }
        pending = self.queue.pending_pods() if hasattr(self.queue, "pending_pods") else []
        return {
            "nodes": nodes,
            "assumed_pods": sorted(cached["assumed_pods"]),
            "pending_pods": [p.full_name() for p in pending],
        }

    def dump_to_log(self) -> None:
        d = self.dump()
        _logger.info("cache dump: %d nodes, %d assumed, %d pending",
                     len(d["nodes"]), len(d["assumed_pods"]),
                     len(d["pending_pods"]))
        for name, info in d["nodes"].items():
            _logger.info("node %s: %s", name, info)

    # -- comparer (debugger/comparer.go) -------------------------------
    def compare(self) -> ComparisonResult:
        """Cache vs store ground truth. Assumed pods are expected to be
        cache-only until their binding lands — not drift."""
        result = ComparisonResult()
        cached = self.cache.dump()
        store_nodes = {n.name for n in self.store.list_nodes()}
        cache_nodes = set(cached["nodes"])
        result.missing_nodes = sorted(store_nodes - cache_nodes)
        result.redundant_nodes = sorted(cache_nodes - store_nodes)

        store_pods = {
            p.full_name() for p in self.store.list_pods() if p.spec.node_name
        }
        cache_pods = set()
        for info in cached["nodes"].values():
            for p in info.pods:
                cache_pods.add(p.pod.full_name())
        assumed = cached["assumed_pods"]
        result.missing_pods = sorted(store_pods - cache_pods)
        result.redundant_pods = sorted(
            k for k in cache_pods - store_pods if k not in assumed
        )
        return result

    # -- signal wiring (debugger/signal.go) ----------------------------
    def listen_for_signal(self, signum: int = signal.SIGUSR2) -> bool:
        """Install the dump-on-signal handler (main thread only — mirrors
        the reference listening for SIGUSR2)."""
        if threading.current_thread() is not threading.main_thread():
            return False

        def handler(sig, frame):
            self.dump_to_log()
            result = self.compare()
            if not result.consistent:
                _logger.warning("cache inconsistent vs store: %s", result)

        signal.signal(signum, handler)
        return True
