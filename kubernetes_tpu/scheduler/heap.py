"""Keyed binary heap with arbitrary less-functions (reference
``internal/heap/heap.go``): supports add/update/delete-by-key and peek/pop,
with an optional gauge recorder (heap.go:243,248)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class Heap:
    def __init__(
        self,
        key_func: Callable[[Any], str],
        less_func: Callable[[Any, Any], bool],
        metric_recorder=None,
        sort_key: Callable[[Any], Any] = None,
    ):
        self._key = key_func
        self._less = less_func
        # Optional total-order key. When set, the ordering key is computed
        # ONCE per insert and sift comparisons become C-speed tuple
        # compares instead of Python less-func calls — the less-func path
        # dominated pod admission at tens of thousands of pods.
        self._sort_key = sort_key
        self._items: List[Any] = []
        self._okeys: List[Any] = []      # parallel to _items (sort_key mode)
        self._index: Dict[str, int] = {}
        self._metric = metric_recorder

    def set_sort_key(self, sort_key: Callable[[Any], Any]) -> None:
        """Install (or clear) the cached total-order key. Only valid on
        an empty heap: existing items were sifted under the previous
        ordering, and rebuilding keys without re-heapifying would corrupt
        the heap property."""
        if self._items:
            raise ValueError("set_sort_key requires an empty heap")
        self._sort_key = sort_key
        self._okeys = []

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, obj: Any) -> bool:
        return self._key(obj) in self._index

    def has_key(self, key: str) -> bool:
        return key in self._index

    def get_by_key(self, key: str) -> Optional[Any]:
        i = self._index.get(key)
        return self._items[i] if i is not None else None

    def list(self) -> List[Any]:
        return list(self._items)

    def add(self, obj: Any) -> None:
        """Insert or update (reference heap.Add)."""
        key = self._key(obj)
        if key in self._index:
            i = self._index[key]
            self._items[i] = obj
            if self._sort_key:
                self._okeys[i] = self._sort_key(obj)
            self._sift_up(i)
            self._sift_down(i)
        else:
            self._items.append(obj)
            if self._sort_key:
                self._okeys.append(self._sort_key(obj))
            self._index[key] = len(self._items) - 1
            self._sift_up(len(self._items) - 1)
            if self._metric:
                self._metric.inc()

    # AddIfNotPresent semantics
    def add_if_not_present(self, obj: Any) -> None:
        if self._key(obj) not in self._index:
            self.add(obj)

    def update(self, obj: Any) -> None:
        self.add(obj)

    def delete(self, obj: Any) -> bool:
        return self.delete_by_key(self._key(obj))

    def delete_by_key(self, key: str) -> bool:
        i = self._index.get(key)
        if i is None:
            return False
        self._swap(i, len(self._items) - 1)
        self._items.pop()
        if self._sort_key:
            self._okeys.pop()
        del self._index[key]
        if i < len(self._items):
            self._sift_up(i)
            self._sift_down(i)
        if self._metric:
            self._metric.dec()
        return True

    def pop_all(self) -> List[Any]:
        """Remove and return every item (arbitrary order) in O(n)."""
        items = self._items
        self._items = []
        self._okeys = []
        self._index = {}
        if self._metric:
            for _ in items:
                self._metric.dec()
        return items

    def replace_all(self, items_in_heap_order: List[Any]) -> None:
        """Install ``items`` as the heap content. The caller must provide
        them already satisfying the heap property (a list sorted by the
        less-function does); no sifting is performed."""
        self._items = list(items_in_heap_order)
        if self._sort_key:
            self._okeys = [self._sort_key(o) for o in self._items]
        self._index = {self._key(o): i for i, o in enumerate(self._items)}
        if self._metric:
            for _ in self._items:
                self._metric.inc()

    def peek(self) -> Optional[Any]:
        return self._items[0] if self._items else None

    def pop(self) -> Any:
        if not self._items:
            raise IndexError("pop from empty heap")
        top = self._items[0]
        self.delete_by_key(self._key(top))
        return top

    # --- internals ----------------------------------------------------
    def _swap(self, i: int, j: int) -> None:
        if i == j:
            return
        items = self._items
        items[i], items[j] = items[j], items[i]
        if self._sort_key:
            okeys = self._okeys
            okeys[i], okeys[j] = okeys[j], okeys[i]
        self._index[self._key(items[i])] = i
        self._index[self._key(items[j])] = j

    def _lt(self, i: int, j: int) -> bool:
        if self._sort_key:
            return self._okeys[i] < self._okeys[j]
        return self._less(self._items[i], self._items[j])

    def _sift_up(self, i: int) -> None:
        while i > 0:
            parent = (i - 1) // 2
            if self._lt(i, parent):
                self._swap(i, parent)
                i = parent
            else:
                break

    def _sift_down(self, i: int) -> None:
        n = len(self._items)
        while True:
            smallest = i
            for child in (2 * i + 1, 2 * i + 2):
                if child < n and self._lt(child, smallest):
                    smallest = child
            if smallest == i:
                return
            self._swap(i, smallest)
            i = smallest
