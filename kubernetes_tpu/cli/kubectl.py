"""ktpu — the CLI (kubectl equivalent).

Behavioral equivalent of the reference's kubectl
(``staging/src/k8s.io/kubectl``; 52-line shim at ``cmd/kubectl``): verbs
over the REST API — get/describe with kubectl-style tables, create/apply
from YAML or JSON manifests, delete, scale, cordon/uncordon/drain, taint,
label, top nodes — plus api-resources and version. Talks HTTP to an
``APIServer`` (``--server`` or ``KTPU_SERVER``); every subcommand is a thin
client of ``RestClient``, mirroring how kubectl is a thin client of
client-go.

Usage:  python -m kubernetes_tpu.cli get pods [-n NS | -A] [-o wide|json]
"""

from __future__ import annotations

import argparse
import io
import json
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

from kubernetes_tpu.api.serialization import from_wire, is_namespaced, to_wire
from kubernetes_tpu.apiserver.rest import KIND_TO_PLURAL, PLURALS, RestClient
from kubernetes_tpu.apiserver.store import ConflictError

VERSION = "v0.1.0-tpu"

# aliases kubectl accepts
_KIND_ALIASES = {
    "po": "Pod", "pod": "Pod", "pods": "Pod",
    "no": "Node", "node": "Node", "nodes": "Node",
    "svc": "Service", "service": "Service", "services": "Service",
    "ep": "Endpoints", "endpoints": "Endpoints",
    "rs": "ReplicaSet", "replicaset": "ReplicaSet", "replicasets": "ReplicaSet",
    "rc": "ReplicationController", "replicationcontroller": "ReplicationController",
    "replicationcontrollers": "ReplicationController",
    "sts": "StatefulSet", "statefulset": "StatefulSet", "statefulsets": "StatefulSet",
    "deploy": "Deployment", "deployment": "Deployment", "deployments": "Deployment",
    "ds": "DaemonSet", "daemonset": "DaemonSet", "daemonsets": "DaemonSet",
    "job": "Job", "jobs": "Job",
    "pvc": "PersistentVolumeClaim", "persistentvolumeclaim": "PersistentVolumeClaim",
    "persistentvolumeclaims": "PersistentVolumeClaim",
    "pv": "PersistentVolume", "persistentvolume": "PersistentVolume",
    "persistentvolumes": "PersistentVolume",
    "sc": "StorageClass", "storageclass": "StorageClass",
    "storageclasses": "StorageClass",
    "csinode": "CSINode", "csinodes": "CSINode",
    "pdb": "PodDisruptionBudget", "poddisruptionbudget": "PodDisruptionBudget",
    "poddisruptionbudgets": "PodDisruptionBudget",
    "ev": "Event", "event": "Event", "events": "Event",
    "ns": "Namespace", "namespace": "Namespace", "namespaces": "Namespace",
    "quota": "ResourceQuota", "resourcequota": "ResourceQuota",
    "resourcequotas": "ResourceQuota",
    "sa": "ServiceAccount", "serviceaccount": "ServiceAccount",
    "serviceaccounts": "ServiceAccount",
    "cj": "CronJob", "cronjob": "CronJob", "cronjobs": "CronJob",
    "hpa": "HorizontalPodAutoscaler",
    "horizontalpodautoscaler": "HorizontalPodAutoscaler",
    "horizontalpodautoscalers": "HorizontalPodAutoscaler",
    "endpointslice": "EndpointSlice", "endpointslices": "EndpointSlice",
    "secret": "Secret", "secrets": "Secret",
    "cm": "ConfigMap", "configmap": "ConfigMap", "configmaps": "ConfigMap",
    "csr": "CertificateSigningRequest",
    "certificatesigningrequest": "CertificateSigningRequest",
    "certificatesigningrequests": "CertificateSigningRequest",
    "role": "Role", "roles": "Role",
    "clusterrole": "ClusterRole", "clusterroles": "ClusterRole",
    "rolebinding": "RoleBinding", "rolebindings": "RoleBinding",
    "clusterrolebinding": "ClusterRoleBinding",
    "clusterrolebindings": "ClusterRoleBinding",
    "crd": "CustomResourceDefinition",
    "crds": "CustomResourceDefinition",
    "customresourcedefinition": "CustomResourceDefinition",
    "customresourcedefinitions": "CustomResourceDefinition",
    "mutatingwebhookconfiguration": "MutatingWebhookConfiguration",
    "mutatingwebhookconfigurations": "MutatingWebhookConfiguration",
    "validatingwebhookconfiguration": "ValidatingWebhookConfiguration",
    "validatingwebhookconfigurations": "ValidatingWebhookConfiguration",
}


def _resolve_kind(token: str) -> str:
    kind = _KIND_ALIASES.get(token.lower())
    if kind is None:
        if token[:1].isupper():
            # CRD-registered kinds pass through VERBATIM ("Widget",
            # "MyWidget") — the server resolves live registrations;
            # guessing a kind from a lowercase token would mangle
            # CamelCase kinds and turn typos into fabricated routes
            return token
        raise SystemExit(
            f"error: the server doesn't have a resource type {token!r} "
            "(for a custom kind, use its exact Kind name, e.g. 'Widget')"
        )
    return kind


def _age(meta) -> str:
    if not meta.creation_timestamp:
        return "<unknown>"
    s = int(time.time() - meta.creation_timestamp)
    if s < 120:
        return f"{s}s"
    if s < 7200:
        return f"{s // 60}m"
    if s < 172800:
        return f"{s // 3600}h"
    return f"{s // 86400}d"


def _table(headers: Sequence[str], rows: List[Sequence[str]], out) -> None:
    widths = [
        max(len(str(h)), *(len(str(r[i])) for r in rows)) if rows else len(str(h))
        for i, h in enumerate(headers)
    ]
    print("   ".join(str(h).ljust(w) for h, w in zip(headers, widths)), file=out)
    for r in rows:
        print("   ".join(str(c).ljust(w) for c, w in zip(r, widths)), file=out)


def _pod_row(p, wide: bool):
    ready = "1/1" if p.status.phase == "Running" else "0/1"
    row = [p.metadata.name, ready, p.status.phase, _age(p.metadata)]
    if wide:
        row += [p.status.pod_ip or "<none>", p.spec.node_name or "<none>"]
    return row


def _node_row(n, wide: bool):
    ready = "Ready"
    for c in n.status.conditions:
        if c.type == "Ready" and c.status != "True":
            ready = "NotReady"
    if n.spec.unschedulable:
        ready += ",SchedulingDisabled"
    row = [n.metadata.name, ready, _age(n.metadata)]
    if wide:
        cpu = n.status.allocatable.get("cpu")
        mem = n.status.allocatable.get("memory")
        row += [str(cpu.value()) if cpu else "?",
                str(mem.value() >> 20) + "Mi" if mem else "?"]
    return row


def _generic_row(obj, wide: bool):
    return [obj.metadata.name, _age(obj.metadata)]


def _event_row(e, wide: bool):
    last = e.last_timestamp or e.metadata.creation_timestamp
    s = int(max(0, time.time() - last))
    age = f"{s}s" if s < 120 else f"{s // 60}m"
    obj = f"{e.involved_object.kind.lower()}/{e.involved_object.name}"
    row = [age, e.type, e.reason, obj, e.message]
    if wide:
        row.insert(4, e.source_component)
        row.append(str(e.count))
    return row


_ROWS = {
    "Pod": (["NAME", "READY", "STATUS", "AGE"],
            ["NAME", "READY", "STATUS", "AGE", "IP", "NODE"], _pod_row),
    "Node": (["NAME", "STATUS", "AGE"],
             ["NAME", "STATUS", "AGE", "CPU", "MEMORY"], _node_row),
    "Event": (["LAST SEEN", "TYPE", "REASON", "OBJECT", "MESSAGE"],
              ["LAST SEEN", "TYPE", "REASON", "OBJECT", "SOURCE",
               "MESSAGE", "COUNT"], _event_row),
}


class Kubectl:
    def __init__(self, client: RestClient, out=None, err=None):
        self.client = client
        self.out = out or sys.stdout
        self.err = err or sys.stderr

    # -- verbs ---------------------------------------------------------
    def get(self, kind_token: str, name: Optional[str], namespace: Optional[str],
            all_namespaces: bool, output: Optional[str],
            selector: str = "", field_selector: str = "") -> int:
        kind = _resolve_kind(kind_token)
        ns = None if all_namespaces or not is_namespaced(kind) else (namespace or "default")
        if name and (selector or field_selector):
            # reference kubectl: selectors never combine with a name
            print("error: selectors may not be used when a resource "
                  "name is given", file=self.err)
            return 1
        if name:
            obj = self.client.get(kind, name, ns or "default")
            if obj is None:
                print(f"Error from server (NotFound): "
                      f"{kind.lower()} {name!r} not found", file=self.err)
                return 1
            objs = [obj]
        else:
            objs, _ = self.client.list(kind, ns, label_selector=selector,
                                       field_selector=field_selector)
        if output == "json":
            docs = [to_wire(o) for o in objs]
            print(json.dumps(docs[0] if name else docs, indent=2), file=self.out)
            return 0
        wide = output == "wide"
        narrow, wides, row_fn = _ROWS.get(kind, (["NAME", "AGE"], ["NAME", "AGE"],
                                                 _generic_row))
        headers = wides if wide else narrow
        _table(headers, [row_fn(o, wide) for o in objs], self.out)
        return 0

    def patch(self, kind_token: str, name: str, patch_str: str,
              namespace: str, patch_type: str) -> int:
        kind = _resolve_kind(kind_token)
        try:
            patch = json.loads(patch_str)
        except json.JSONDecodeError as e:
            print(f"error: invalid patch JSON: {e}", file=self.err)
            return 1
        try:
            obj = self.client.patch(kind, name, patch, namespace,
                                    patch_type)
        except KeyError as e:
            print(f"Error from server (NotFound): {e}", file=self.err)
            return 1
        except (PermissionError, ConflictError, RuntimeError) as e:
            print(f"Error from server: {e}", file=self.err)
            return 1
        print(f"{kind.lower()}/{obj.metadata.name} patched", file=self.out)
        return 0

    def logs(self, name: str, namespace: str, container: str = "") -> int:
        """kubectl logs: the pods/log subresource proxied through the
        apiserver to the owning kubelet. Errors arrive as HTTP status
        codes (400/403/404), never in-band in the log text."""
        try:
            text = self.client.pod_logs(namespace, name, container)
        except KeyError as e:
            print(f"Error from server (NotFound): {e}", file=self.err)
            return 1
        except PermissionError as e:
            print(f"Error from server (Forbidden): {e}", file=self.err)
            return 1
        except RuntimeError as e:
            print(f"Error from server: {e}", file=self.err)
            return 1
        self.out.write(text)
        return 0

    def exec_cmd(self, name: str, namespace: str, container: str,
                 command: list) -> int:
        """kubectl exec: pods/exec proxied through the apiserver to the
        owning kubelet's CRI (reference staging/src/k8s.io/kubectl/pkg/
        cmd/exec/exec.go)."""
        if not command:
            print("error: you must specify a command (after --)",
                  file=self.err)
            return 1
        try:
            rc, output = self.client.pod_exec(namespace, name, container,
                                              command)
        except KeyError as e:
            print(f"Error from server (NotFound): {e}", file=self.err)
            return 1
        except PermissionError as e:
            print(f"Error from server (Forbidden): {e}", file=self.err)
            return 1
        except RuntimeError as e:
            print(f"Error from server: {e}", file=self.err)
            return 1
        if output:
            self.out.write(output)
        return rc

    # -- rollout (reference staging/src/k8s.io/kubectl/pkg/cmd/rollout/
    # rollout.go: status/history/undo against the deployment
    # controller's revision-annotated ReplicaSets) ----------------------
    def _deployment_and_rses(self, name: str, namespace: str):
        deploy = self.client.get("Deployment", name, namespace)
        if deploy is None:
            raise KeyError(f"deployment {name!r} not found")
        rses, _rv = self.client.list("ReplicaSet", namespace)
        owned = [
            rs for rs in rses
            if any(r.get("controller") and r.get("kind") == "Deployment"
                   and r.get("uid") == deploy.metadata.uid
                   for r in rs.metadata.owner_references)
        ]
        return deploy, owned

    def rollout_status(self, name: str, namespace: str) -> int:
        from kubernetes_tpu.controllers.deployment import template_hash

        deploy, owned = self._deployment_and_rses(name, namespace)
        want_hash = template_hash(deploy.template)
        current = next(
            (rs for rs in owned
             if rs.metadata.labels.get("pod-template-hash") == want_hash),
            None)
        ready = current.status.ready_replicas if current else 0
        old_live = sum(rs.status.replicas for rs in owned
                       if current is None
                       or rs.metadata.uid != current.metadata.uid)
        if current is not None and ready >= deploy.replicas \
                and old_live == 0:
            print(f'deployment "{name}" successfully rolled out',
                  file=self.out)
            return 0
        print(f'Waiting for deployment "{name}" rollout to finish: '
              f'{ready} of {deploy.replicas} updated replicas are '
              f'available...', file=self.out)
        return 1

    def rollout_history(self, name: str, namespace: str) -> int:
        from kubernetes_tpu.controllers.deployment import (
            CHANGE_CAUSE_ANNOTATION,
            rs_revision,
        )

        _deploy, owned = self._deployment_and_rses(name, namespace)
        print(f'deployment.apps/{name}', file=self.out)
        print(f'{"REVISION":<10}CHANGE-CAUSE', file=self.out)
        for rs in sorted(owned, key=rs_revision):
            cause = rs.metadata.annotations.get(
                CHANGE_CAUSE_ANNOTATION) or "<none>"
            print(f'{rs_revision(rs):<10}{cause}', file=self.out)
        return 0

    def rollout_undo(self, name: str, namespace: str,
                     to_revision: int = 0) -> int:
        from kubernetes_tpu.controllers.deployment import rs_revision

        deploy, owned = self._deployment_and_rses(name, namespace)
        if not owned:
            print(f"error: no rollout history found for deployment "
                  f"{name!r}", file=self.err)
            return 1
        by_rev = sorted(owned, key=rs_revision)
        if to_revision:
            target = next((rs for rs in by_rev
                           if rs_revision(rs) == to_revision), None)
            if target is None:
                print(f"error: unable to find revision {to_revision} "
                      f"of deployment {name!r}", file=self.err)
                return 1
        else:
            if len(by_rev) < 2:
                print(f"error: no previous revision to roll back to "
                      f"for deployment {name!r}", file=self.err)
                return 1
            target = by_rev[-2]   # the revision before current
        import copy as _copy
        import json as _json

        from kubernetes_tpu.apiserver.store import ConflictError

        template = _json.loads(_json.dumps(target.template or {}))
        labels = dict(template.get("metadata", {}).get("labels") or {})
        labels.pop("pod-template-hash", None)
        template.setdefault("metadata", {})["labels"] = labels
        # read-modify-write with conflict retry: the deployment
        # controller's status writes race this PUT (real kubectl undoes
        # via PATCH, which the server merges; retrying the PUT against
        # a fresh read is the same fixed point)
        for attempt in range(5):
            updated = _copy.copy(deploy)
            updated.template = template
            try:
                self.client.update(updated)
                break
            except ConflictError:
                if attempt == 4:
                    raise
                deploy = self.client.get("Deployment", name, namespace)
        print(f'deployment.apps/{name} rolled back', file=self.out)
        return 0

    def describe(self, kind_token: str, name: str, namespace: str) -> int:
        kind = _resolve_kind(kind_token)
        obj = self.client.get(kind, name, namespace)
        if obj is None:
            print(f"Error from server (NotFound): {kind.lower()} {name!r} not found",
                  file=self.err)
            return 1
        doc = to_wire(obj)
        import yaml

        print(yaml.safe_dump(doc, sort_keys=False, default_flow_style=False),
              file=self.out)
        # Events section (reference kubectl describe: related events last)
        if kind != "Event":
            try:
                events, _ = self.client.list("Event", namespace)
            except Exception:  # noqa: BLE001 — older servers without events
                events = []
            related = [
                e for e in events
                if e.involved_object.kind == kind
                and e.involved_object.name == name
            ]
            if related:
                print("Events:", file=self.out)
                _table(["TYPE", "REASON", "MESSAGE", "COUNT"],
                       [[e.type, e.reason, e.message, str(e.count)]
                        for e in related], self.out)
        return 0

    def edit(self, kind_token: str, name: str, namespace: str) -> int:
        """kubectl edit: dump the live object to a temp YAML file, run
        $EDITOR on it, PUT the result back (conflict-retried like
        rollout undo; reference kubectl/pkg/cmd/editor). An unchanged
        buffer is a no-op ("Edit cancelled")."""
        import os
        import subprocess
        import tempfile

        import yaml

        from kubernetes_tpu.apiserver.store import ConflictError

        kind = _resolve_kind(kind_token)
        obj = self.client.get(kind, name, namespace)
        if obj is None:
            print(f"Error from server (NotFound): {kind.lower()} "
                  f"{name!r} not found", file=self.err)
            return 1
        editor = os.environ.get("EDITOR") or os.environ.get("VISUAL") \
            or "vi"
        original = yaml.safe_dump(to_wire(obj), sort_keys=False,
                                  default_flow_style=False)
        with tempfile.NamedTemporaryFile(
                "w", suffix=".yaml", prefix=f"ktpu-edit-{name}-",
                delete=False) as f:
            f.write(original)
            path = f.name
        try:
            import shlex

            rc = subprocess.call(f"{editor} {shlex.quote(path)}",
                                 shell=True)
            if rc != 0:
                print(f"error: editor {editor!r} exited {rc}",
                      file=self.err)
                return 1
            with open(path) as f:
                edited = f.read()
            if edited == original:
                print("Edit cancelled, no changes made.", file=self.out)
                return 0
            try:
                doc = yaml.safe_load(edited)
            except yaml.YAMLError as e:
                saved = path + ".rej"
                os.replace(path, saved)
                path = None   # preserved for the user, skip unlink
                print(f"error: edited buffer is not valid YAML ({e}); "
                      f"your edits are saved at {saved}", file=self.err)
                return 1
            updated = from_wire(doc, kind)
            for attempt in range(5):
                try:
                    self.client.update(updated)
                    break
                except ConflictError as e:
                    if attempt == 4:
                        print(f"Error from server (Conflict): {e}",
                              file=self.err)
                        return 1
                    live = self.client.get(kind, name, namespace)
                    if live is None:
                        print(f"Error from server (NotFound): "
                              f"{kind.lower()} {name!r} was deleted "
                              f"while being edited", file=self.err)
                        return 1
                    updated.metadata.resource_version = \
                        live.metadata.resource_version
            print(f"{kind.lower()}/{name} edited", file=self.out)
            return 0
        finally:
            if path is not None:
                os.unlink(path)

    def port_forward(self, name: str, namespace: str, local_port: int,
                     remote_port: int, once: bool = False) -> int:
        """kubectl port-forward: a local listener proxies each
        connection's payload through the apiserver's pods/{name}/
        portforward subresource to the owning kubelet's runtime
        (reference kubectl/pkg/cmd/portforward over SPDY streams; this
        analog exchanges one request/response per connection).
        ``once`` serves a single connection then returns (tests)."""
        import base64
        import socket as socketlib

        srv = socketlib.socket(socketlib.AF_INET, socketlib.SOCK_STREAM)
        srv.setsockopt(socketlib.SOL_SOCKET, socketlib.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", local_port))
        bound_port = srv.getsockname()[1]
        srv.listen(4)
        print(f"Forwarding from 127.0.0.1:{bound_port} -> "
              f"{remote_port}", file=self.out)
        self.forwarding_port = bound_port   # tests read the ephemeral port
        try:
            while True:
                conn, _addr = srv.accept()
                try:
                    conn.settimeout(2.0)
                    chunks = []
                    while True:
                        try:
                            data = conn.recv(65536)
                        except socketlib.timeout:
                            # TCP has no message boundaries: EOF (the
                            # client's shutdown) or silence ends the
                            # request — never a short recv, which would
                            # truncate multi-segment payloads
                            break
                        if not data:
                            break
                        chunks.append(data)
                    payload = b"".join(chunks)
                    code, resp = self.client._request(
                        "POST",
                        self.client._path("Pod", namespace, name,
                                          "portforward"),
                        {"port": remote_port,
                         "data": base64.b64encode(payload).decode()},
                    )
                    if code >= 400:
                        msg = resp.get("message", "") if isinstance(
                            resp, dict) else str(resp)
                        conn.sendall(f"error: {msg}".encode())
                        failed = True
                    else:
                        conn.sendall(base64.b64decode(
                            resp.get("data", "")))
                        failed = False
                finally:
                    conn.close()
                if once:
                    return 1 if failed else 0
        except KeyboardInterrupt:
            return 0
        finally:
            srv.close()

    def _load_manifests(self, path: str) -> List[Any]:
        import yaml

        if path == "-":
            raw = sys.stdin.read()
        else:
            with open(path) as f:
                raw = f.read()
        docs = list(yaml.safe_load_all(raw))
        objs = []
        for doc in docs:
            if not doc:
                continue
            if "kind" not in doc:
                raise SystemExit("error: manifest missing 'kind'")
            objs.append(from_wire(doc))
        return objs

    def create(self, filename: str, namespace: Optional[str]) -> int:
        for obj in self._load_manifests(filename):
            if namespace and is_namespaced(type(obj).__name__):
                obj.metadata.namespace = namespace
            created = self.client.create(obj)
            print(f"{type(created).__name__.lower()}/{created.metadata.name} created",
                  file=self.out)
        return 0

    def apply(self, filename: str, namespace: Optional[str]) -> int:
        """Create-or-update (the declarative path)."""
        for obj in self._load_manifests(filename):
            kind = type(obj).__name__
            if namespace and is_namespaced(kind):
                obj.metadata.namespace = namespace
            existing = self.client.get(kind, obj.metadata.name,
                                       obj.metadata.namespace)
            if existing is None:
                self.client.create(obj)
                print(f"{kind.lower()}/{obj.metadata.name} created", file=self.out)
            else:
                obj.metadata.resource_version = existing.metadata.resource_version
                obj.metadata.uid = existing.metadata.uid
                self.client.update(obj)
                print(f"{kind.lower()}/{obj.metadata.name} configured", file=self.out)
        return 0

    def delete(self, kind_token: str, name: str, namespace: str) -> int:
        kind = _resolve_kind(kind_token)
        if self.client.delete(kind, name, namespace):
            print(f"{kind.lower()} \"{name}\" deleted", file=self.out)
            return 0
        print(f"Error from server (NotFound): {kind.lower()} {name!r} not found",
              file=self.err)
        return 1

    def scale(self, kind_token: str, name: str, replicas: int, namespace: str) -> int:
        kind = _resolve_kind(kind_token)
        obj = self.client.get(kind, name, namespace)
        if obj is None or not hasattr(obj, "replicas"):
            print(f"error: cannot scale {kind_token} {name!r}", file=self.err)
            return 1
        obj.replicas = replicas
        self.client.update(obj)
        print(f"{kind.lower()}/{name} scaled", file=self.out)
        return 0

    def cordon(self, name: str, on: bool) -> int:
        node = self.client.get("Node", name)
        if node is None:
            print(f"error: node {name!r} not found", file=self.err)
            return 1
        node.spec.unschedulable = on
        self.client.update(node)
        print(f"node/{name} {'cordoned' if on else 'uncordoned'}", file=self.out)
        return 0

    def drain(self, name: str) -> int:
        """cordon + evict all pods on the node (kubectl drain semantics,
        sans daemonset handling)."""
        rc = self.cordon(name, True)
        if rc:
            return rc
        pods, _ = self.client.list("Pod")
        for p in pods:
            if p.spec.node_name == name:
                self.client.delete("Pod", p.metadata.name, p.metadata.namespace)
                print(f"pod/{p.metadata.name} evicted", file=self.out)
        return 0

    def taint(self, name: str, spec: str) -> int:
        """ktpu taint <node> key=value:Effect  (suffix '-' removes)."""
        from kubernetes_tpu.api.types import Taint

        node = self.client.get("Node", name)
        if node is None:
            print(f"error: node {name!r} not found", file=self.err)
            return 1
        remove = spec.endswith("-")
        spec = spec.rstrip("-")
        kv, _, effect = spec.partition(":")
        key, _, value = kv.partition("=")
        if remove:
            node.spec.taints = [t for t in node.spec.taints if t.key != key]
        else:
            node.spec.taints = [t for t in node.spec.taints if t.key != key] + [
                Taint(key=key, value=value, effect=effect or "NoSchedule")
            ]
        self.client.update(node)
        print(f"node/{name} {'untainted' if remove else 'tainted'}", file=self.out)
        return 0

    def label(self, kind_token: str, name: str, spec: str, namespace: str) -> int:
        kind = _resolve_kind(kind_token)
        obj = self.client.get(kind, name, namespace)
        if obj is None:
            print(f"error: {kind_token} {name!r} not found", file=self.err)
            return 1
        if spec.endswith("-"):
            obj.metadata.labels.pop(spec[:-1], None)
        else:
            k, _, v = spec.partition("=")
            obj.metadata.labels[k] = v
        self.client.update(obj)
        print(f"{kind.lower()}/{name} labeled", file=self.out)
        return 0

    def auth_can_i(self, verb: str, resource: str, namespace: str,
                   name: str = "") -> int:
        """kubectl auth can-i VERB RESOURCE — a SelfSubjectAccessReview
        round-trip (reference kubectl/pkg/cmd/auth/cani.go); exit code 0
        for yes, 1 for no (upstream contract)."""
        allowed = self.client.can_i(verb, resource, namespace, name)
        print("yes" if allowed else "no", file=self.out)
        return 0 if allowed else 1

    def top_nodes(self) -> int:
        """Requested/allocatable per node (the /metrics/resources view)."""
        nodes, _ = self.client.list("Node")
        pods, _ = self.client.list("Pod")
        rows = []
        for n in nodes:
            cpu_req = sum(
                (q.milli_value() for p in pods if p.spec.node_name == n.metadata.name
                 for c in p.spec.containers
                 for r, q in c.resources.requests.items() if r == "cpu"),
            )
            alloc = n.status.allocatable.get("cpu")
            alloc_m = alloc.milli_value() if alloc else 0
            pct = f"{100 * cpu_req // alloc_m}%" if alloc_m else "?"
            rows.append([n.metadata.name, f"{cpu_req}m", pct])
        _table(["NAME", "CPU(requests)", "CPU%"], rows, self.out)
        return 0

    def api_resources(self) -> int:
        """Server discovery first (GET /api/v1 — includes live CRD
        registrations, like real kubectl's discovery client); the local
        table is the offline fallback."""
        rows = []
        try:
            code, payload = self.client._request("GET", "/api/v1")
            if code == 200:
                rows = [
                    [r["name"], r["kind"],
                     str(bool(r.get("namespaced"))).lower()]
                    for r in payload.get("resources", [])
                ]
        except Exception:  # noqa: BLE001 — discovery is best-effort
            pass
        if not rows:
            rows = [
                [plural, kind, str(is_namespaced(kind)).lower()]
                for plural, kind in sorted(PLURALS.items())
            ]
        _table(["NAME", "KIND", "NAMESPACED"], rows, self.out)
        return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ktpu", description=__doc__.split("\n")[0])
    p.add_argument("--server", default=None, help="API server URL "
                   "(default: $KTPU_SERVER)")
    p.add_argument("--token", default="", help="bearer token")
    sub = p.add_subparsers(dest="verb", required=True)

    pa = sub.add_parser("patch")
    pa.add_argument("kind")
    pa.add_argument("name")
    pa.add_argument("-p", "--patch", required=True,
                    help="JSON merge patch (or RFC 6902 array with --type=json)")
    pa.add_argument("--type", dest="patch_type", default="merge",
                    choices=["merge", "json"])
    pa.add_argument("-n", "--namespace", default="default")

    lg = sub.add_parser("logs")
    lg.add_argument("pod_name")
    lg.add_argument("-c", "--container", default="")
    lg.add_argument("-n", "--namespace", default="default")

    ex = sub.add_parser("exec")
    ex.add_argument("pod_name")
    ex.add_argument("-c", "--container", default="")
    ex.add_argument("-n", "--namespace", default="default")
    ex.add_argument("command", nargs=argparse.REMAINDER,
                    help="command to run (after --)")

    ed = sub.add_parser("edit")
    ed.add_argument("kind")
    ed.add_argument("name")
    ed.add_argument("-n", "--namespace", default="default")

    pf = sub.add_parser("port-forward")
    pf.add_argument("pod_name")
    pf.add_argument("ports", help="LOCAL:REMOTE (0 picks an ephemeral "
                    "local port) or REMOTE")
    pf.add_argument("-n", "--namespace", default="default")
    pf.add_argument("--once", action="store_true",
                    help="serve one connection, then exit")

    ro = sub.add_parser("rollout")
    ro.add_argument("subverb", choices=["status", "history", "undo"])
    ro.add_argument("resource", help='e.g. deployment/web (or "deployment web")')
    ro.add_argument("res_name", nargs="?", default="")
    ro.add_argument("--to-revision", type=int, default=0)
    ro.add_argument("-n", "--namespace", default="default")

    g = sub.add_parser("get")
    g.add_argument("kind")
    g.add_argument("name", nargs="?")
    g.add_argument("-n", "--namespace", default=None)
    g.add_argument("-A", "--all-namespaces", action="store_true")
    g.add_argument("-o", "--output", choices=["wide", "json"], default=None)
    g.add_argument("-l", "--selector", default="",
                   help="label selector, e.g. app=web,tier!=cache")
    g.add_argument("--field-selector", default="",
                   help="field selector, e.g. spec.nodeName=n1")

    d = sub.add_parser("describe")
    d.add_argument("kind")
    d.add_argument("name")
    d.add_argument("-n", "--namespace", default="default")

    for verb in ("create", "apply"):
        c = sub.add_parser(verb)
        c.add_argument("-f", "--filename", required=True)
        c.add_argument("-n", "--namespace", default=None)

    dl = sub.add_parser("delete")
    dl.add_argument("kind")
    dl.add_argument("name")
    dl.add_argument("-n", "--namespace", default="default")

    s = sub.add_parser("scale")
    s.add_argument("kind")
    s.add_argument("name")
    s.add_argument("--replicas", type=int, required=True)
    s.add_argument("-n", "--namespace", default="default")

    for verb in ("cordon", "uncordon", "drain"):
        cv = sub.add_parser(verb)
        cv.add_argument("name")

    t = sub.add_parser("taint")
    t.add_argument("name")
    t.add_argument("spec")

    lb = sub.add_parser("label")
    lb.add_argument("kind")
    lb.add_argument("name")
    lb.add_argument("spec")
    lb.add_argument("-n", "--namespace", default="default")

    au = sub.add_parser("auth")
    au.add_argument("subverb", choices=["can-i"])
    au.add_argument("can_verb")
    au.add_argument("resource")
    au.add_argument("obj_name", nargs="?", default="")
    au.add_argument("-n", "--namespace", default="")

    tp = sub.add_parser("top")
    tp.add_argument("what", choices=["nodes"])

    sub.add_parser("api-resources")
    sub.add_parser("version")
    return p


def run_command(argv: Sequence[str], client: Optional[RestClient] = None,
                out=None, err=None) -> int:
    import os

    args = build_parser().parse_args(argv)
    out = out or sys.stdout
    err = err or sys.stderr
    if args.verb == "version":
        print(f"Client Version: {VERSION}", file=out)
        return 0
    if client is None:
        server = args.server or os.environ.get("KTPU_SERVER")
        if not server:
            print("error: no API server (--server or $KTPU_SERVER)", file=err)
            return 1
        client = RestClient(server, token=args.token)
    k = Kubectl(client, out=out, err=err)
    try:
        return _dispatch(k, args)
    except ConflictError as e:
        print(f"Error from server (Conflict): {e}", file=err)
        return 1
    except PermissionError as e:
        print(f"Error from server (Forbidden/Invalid): {e}", file=err)
        return 1
    except KeyError as e:
        print(f"Error from server (NotFound): {e}", file=err)
        return 1
    except RuntimeError as e:
        print(f"Error from server: {e}", file=err)
        return 1


def _dispatch(k: "Kubectl", args) -> int:
    if args.verb == "get":
        return k.get(args.kind, args.name, args.namespace, args.all_namespaces,
                     args.output, args.selector, args.field_selector)
    if args.verb == "patch":
        return k.patch(args.kind, args.name, args.patch, args.namespace,
                       args.patch_type)
    if args.verb == "logs":
        return k.logs(args.pod_name, args.namespace, args.container)
    if args.verb == "exec":
        command = list(args.command)
        if command and command[0] == "--":
            command = command[1:]
        return k.exec_cmd(args.pod_name, args.namespace, args.container,
                          command)
    if args.verb == "edit":
        return k.edit(args.kind, args.name, args.namespace)
    if args.verb == "port-forward":
        spec = args.ports
        try:
            if ":" in spec:
                local_s, _, remote_s = spec.partition(":")
                local, remote = int(local_s), int(remote_s)
            else:
                local = remote = int(spec)
        except ValueError:
            print(f"error: invalid port specification {spec!r} "
                  "(want LOCAL:REMOTE or REMOTE)", file=k.err)
            return 1
        return k.port_forward(args.pod_name, args.namespace, local,
                              remote, once=args.once)
    if args.verb == "rollout":
        resource, name = args.resource, args.res_name
        if "/" in resource:
            resource, _, name = resource.partition("/")
        if resource not in ("deployment", "deployments", "deploy"):
            print(f"error: rollout supports deployments, got {resource!r}",
                  file=k.err)
            return 1
        if not name:
            print("error: a deployment name is required", file=k.err)
            return 1
        if args.subverb == "status":
            return k.rollout_status(name, args.namespace)
        if args.subverb == "history":
            return k.rollout_history(name, args.namespace)
        return k.rollout_undo(name, args.namespace, args.to_revision)
    if args.verb == "describe":
        return k.describe(args.kind, args.name, args.namespace)
    if args.verb == "create":
        return k.create(args.filename, args.namespace)
    if args.verb == "apply":
        return k.apply(args.filename, args.namespace)
    if args.verb == "delete":
        return k.delete(args.kind, args.name, args.namespace)
    if args.verb == "scale":
        return k.scale(args.kind, args.name, args.replicas, args.namespace)
    if args.verb == "cordon":
        return k.cordon(args.name, True)
    if args.verb == "uncordon":
        return k.cordon(args.name, False)
    if args.verb == "drain":
        return k.drain(args.name)
    if args.verb == "taint":
        return k.taint(args.name, args.spec)
    if args.verb == "label":
        return k.label(args.kind, args.name, args.spec, args.namespace)
    if args.verb == "auth":
        return k.auth_can_i(args.can_verb, args.resource, args.namespace,
                            args.obj_name)
    if args.verb == "top":
        return k.top_nodes()
    if args.verb == "api-resources":
        return k.api_resources()
    return 2


def main() -> None:
    sys.exit(run_command(sys.argv[1:]))


if __name__ == "__main__":
    main()
