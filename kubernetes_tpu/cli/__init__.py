from kubernetes_tpu.cli.kubectl import main, run_command
