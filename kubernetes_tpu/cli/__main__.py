from kubernetes_tpu.cli.kubectl import main

main()
