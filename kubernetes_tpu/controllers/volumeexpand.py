"""Volume expand controller (reference ``pkg/controller/volume/
expand/expand_controller.go``): a bound PVC whose ``requests.storage``
grew past its PV's capacity gets the PV resized (the fake in-process
provider "resizes" instantly, like the harness's other volume
plumbing); shrink requests are refused — volumes only grow
(expand_controller.go pvcUpdate: new > old only).
"""

from __future__ import annotations

import logging

from kubernetes_tpu.controllers.base import Controller, split_key

_logger = logging.getLogger(__name__)


class VolumeExpandController(Controller):
    name = "volumeexpand"

    def register(self) -> None:
        self.factory.informer_for("PersistentVolumeClaim") \
            .add_event_handler(
                on_add=self.enqueue,
                on_update=lambda old, new: self.enqueue(new),
            )

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pvc = self.store.get_pvc(ns, name)
        if pvc is None or not pvc.volume_name:
            return
        want = pvc.requests.get("storage")
        if want is None:
            return
        pv = self.store.get_pv(pvc.volume_name)
        if pv is None:
            return
        have = pv.capacity.get("storage")
        if have is None or have.value() >= want.value():
            return

        def mutate(p) -> bool:
            cap = p.capacity.get("storage")
            if cap is not None and cap.value() >= want.value():
                return False
            p.capacity = dict(p.capacity)
            p.capacity["storage"] = want
            return True

        self.store.mutate_object(
            "PersistentVolume", "", pvc.volume_name, mutate
        )
        _logger.info("expanded PV %s to %s", pvc.volume_name, want)
