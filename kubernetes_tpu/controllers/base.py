"""Shared controller scaffolding: informer → workqueue → reconcile workers.

The universal control-loop shape from the reference's
``pkg/controller/`` packages: event handlers enqueue object keys on a
rate-limited workqueue; worker threads pop keys and reconcile observed →
desired state, re-queuing with backoff on error and forgetting the key on
success (e.g. ``pkg/controller/replicaset/replica_set.go`` syncHandler
loop).
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client import RateLimitingQueue, SharedInformerFactory

_logger = logging.getLogger(__name__)


class Controller:
    """Base: subclasses set ``name``, wire handlers in ``register`` and
    implement ``sync(key)``. Controllers needing a periodic resync
    backstop set ``RESYNC_SECONDS`` and override ``resync()`` — the base
    runs the tick thread (started in ``run``, joined in ``stop``) so the
    boilerplate exists exactly once."""

    name = "controller"
    workers = 1
    max_requeues = 10
    RESYNC_SECONDS: Optional[float] = None

    def __init__(self, store: ClusterStore, factory: SharedInformerFactory):
        self.store = store
        self.factory = factory
        self.queue = RateLimitingQueue()
        self._threads: List[threading.Thread] = []
        self._stopped = False
        self._tick_stop = threading.Event()
        self._tick_thread: Optional[threading.Thread] = None
        self.register()

    # -- subclass surface ----------------------------------------------
    def register(self) -> None:
        raise NotImplementedError

    def sync(self, key: str) -> None:
        raise NotImplementedError

    def resync(self) -> None:
        """Periodic enqueue hook, driven every ``RESYNC_SECONDS``."""

    def _tick_loop(self) -> None:
        while not self._tick_stop.wait(self.RESYNC_SECONDS):
            try:
                self.resync()
            except Exception:  # noqa: BLE001 — ticks must not die
                _logger.exception("%s: resync failed", self.name)

    # ------------------------------------------------------------------
    def enqueue(self, obj) -> None:
        meta = obj.metadata
        ns = getattr(meta, "namespace", "")
        self.queue.add(f"{ns}/{meta.name}" if ns else meta.name)

    def enqueue_key(self, key: str) -> None:
        self.queue.add(key)

    def run(self) -> None:
        for i in range(self.workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"{self.name}-{i}")
            t.start()
            self._threads.append(t)
        if self.RESYNC_SECONDS is not None:
            self._tick_thread = threading.Thread(
                target=self._tick_loop, daemon=True,
                name=f"{self.name}-tick",
            )
            self._tick_thread.start()

    def _worker(self) -> None:
        while not self._stopped:
            key = self.queue.get(timeout=0.5)
            if key is None:
                if self.queue.shutting_down:
                    return
                continue
            try:
                self.sync(key)
            except Exception:  # noqa: BLE001 — reconcile must retry, not die
                if self.queue.num_requeues(key) < self.max_requeues:
                    _logger.exception("%s: sync %s failed; requeueing",
                                      self.name, key)
                    self.queue.add_rate_limited(key)
                else:
                    _logger.exception("%s: sync %s failed too many times; "
                                      "dropping", self.name, key)
                    self.queue.forget(key)
            else:
                self.queue.forget(key)
            finally:
                self.queue.done(key)

    def stop(self) -> None:
        self._stopped = True
        self._tick_stop.set()
        self.queue.shutdown()
        if self._tick_thread is not None:
            self._tick_thread.join(timeout=2.0)
        for t in self._threads:
            t.join(timeout=2.0)


def split_key(key: str) -> tuple:
    ns, _, name = key.partition("/")
    return ns, name


def owner_ref(kind: str, obj) -> dict:
    """controller=True OwnerReference (reference metav1.OwnerReference)."""
    return {
        "kind": kind,
        "name": obj.metadata.name,
        "uid": obj.metadata.uid,
        "controller": True,
    }


def is_owned_by(pod, kind: str, owner) -> bool:
    return any(
        r.get("controller") and r.get("kind") == kind
        and r.get("uid") == owner.metadata.uid
        for r in pod.metadata.owner_references
    )


def controller_of(obj) -> Optional[dict]:
    for r in obj.metadata.owner_references:
        if r.get("controller"):
            return r
    return None


def with_status(obj, status):
    """Shallow-copy ``obj`` carrying ``status`` — controllers must never
    mutate store/informer-cached instances in place (watch consumers
    compare old vs new objects)."""
    import copy

    new = copy.copy(obj)
    new.metadata = copy.copy(obj.metadata)
    new.status = status
    return new
