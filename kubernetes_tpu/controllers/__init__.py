"""Control loops + the controller manager.

Behavioral equivalent of the reference's kube-controller-manager
(``cmd/kube-controller-manager/app/controllermanager.go:387``
NewControllerInitializers registers 38 loops; this build implements the
loops the scheduling/perf surface exercises): each controller follows the
informer → rate-limited workqueue → reconcile-worker shape.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client import (
    LeaderElectionConfig,
    LeaderElector,
    SharedInformerFactory,
)
from kubernetes_tpu.controllers.attachdetach import AttachDetachController
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.bootstraptoken import (
    BootstrapSignerController,
    TokenCleanerController,
)
from kubernetes_tpu.controllers.certificates import (
    CSRApprovingController,
    CSRCleanerController,
    CSRSigningController,
)
from kubernetes_tpu.controllers.clusterroleaggregation import (
    ClusterRoleAggregationController,
)
from kubernetes_tpu.controllers.ephemeralvolume import (
    EphemeralVolumeController,
)
from kubernetes_tpu.controllers.endpointslicemirroring import (
    EndpointSliceMirroringController,
)
from kubernetes_tpu.controllers.volumeexpand import VolumeExpandController
from kubernetes_tpu.controllers.cronjob import CronJobController
from kubernetes_tpu.controllers.daemonset import DaemonSetController
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.disruption import DisruptionController
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.endpointslice import EndpointSliceController
from kubernetes_tpu.controllers.garbagecollector import GarbageCollector
from kubernetes_tpu.controllers.horizontalpodautoscaler import (
    HorizontalPodAutoscalerController,
)
from kubernetes_tpu.controllers.job import JobController
from kubernetes_tpu.controllers.namespace import NamespaceController
from kubernetes_tpu.controllers.nodeipam import NodeIpamController
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.controllers.nodettl import TTLController
from kubernetes_tpu.controllers.podgc import PodGCController
from kubernetes_tpu.controllers.replicaset import (
    ReplicaSetController,
    ReplicationController,
)
from kubernetes_tpu.controllers.resourcequota import ResourceQuotaController
from kubernetes_tpu.controllers.rootcacertpublisher import (
    RootCACertPublisher,
)
from kubernetes_tpu.controllers.serviceaccount import ServiceAccountController
from kubernetes_tpu.controllers.serviceaccounttoken import TokensController
from kubernetes_tpu.controllers.statefulset import StatefulSetController
from kubernetes_tpu.controllers.ttlafterfinished import (
    TTLAfterFinishedController,
)
from kubernetes_tpu.controllers.volume import PersistentVolumeController
from kubernetes_tpu.controllers.volumeprotection import (
    PVCProtectionController,
    PVProtectionController,
)


def new_controller_initializers() -> Dict[str, Callable]:
    """name -> constructor (controllermanager.go:387)."""
    # imported here, not at module top: the autoscaler's controller
    # imports controllers.base, so a top-level import would be circular
    # whichever package loads first
    from kubernetes_tpu.autoscaler.controller import ClusterAutoscaler

    return {
        "replicaset": ReplicaSetController,
        "replicationcontroller": ReplicationController,
        "deployment": DeploymentController,
        "statefulset": StatefulSetController,
        "daemonset": DaemonSetController,
        "job": JobController,
        "cronjob": CronJobController,
        "ttl-after-finished": TTLAfterFinishedController,
        "endpoints": EndpointsController,
        "endpointslice": EndpointSliceController,
        "horizontalpodautoscaler": HorizontalPodAutoscalerController,
        "garbagecollector": GarbageCollector,
        "nodelifecycle": NodeLifecycleController,
        "nodeipam": NodeIpamController,
        "persistentvolume-binder": PersistentVolumeController,
        "attachdetach": AttachDetachController,
        "disruption": DisruptionController,
        "namespace": NamespaceController,
        "resourcequota": ResourceQuotaController,
        "serviceaccount": ServiceAccountController,
        "serviceaccount-token": TokensController,
        "root-ca-cert-publisher": RootCACertPublisher,
        "podgc": PodGCController,
        "ttl": TTLController,
        "pvc-protection": PVCProtectionController,
        "pv-protection": PVProtectionController,
        "csrapproving": CSRApprovingController,
        "csrsigning": CSRSigningController,
        "csrcleaner": CSRCleanerController,
        "bootstrapsigner": BootstrapSignerController,
        "tokencleaner": TokenCleanerController,
        "endpointslicemirroring": EndpointSliceMirroringController,
        "volumeexpand": VolumeExpandController,
        "ephemeral-volume": EphemeralVolumeController,
        "clusterrole-aggregation": ClusterRoleAggregationController,
        # no kube-controller-manager analog — upstream ships the
        # cluster-autoscaler as its own binary — but it rides the same
        # loop scaffolding; with an empty NodeGroupRegistry (the
        # default) every pass is a no-op, so enabling it here is safe
        "clusterautoscaler": ClusterAutoscaler,
    }


class ControllerManager:
    """kube-controller-manager: runs the selected loops behind optional
    leader election, over one shared informer factory."""

    def __init__(
        self,
        store: ClusterStore,
        controllers: Optional[List[str]] = None,
        leader_elect: bool = False,
        identity: str = "kube-controller-manager-0",
    ):
        self.store = store
        self.factory = SharedInformerFactory(store)
        inits = new_controller_initializers()
        names = controllers if controllers is not None else list(inits)
        self.controllers: Dict[str, Controller] = {
            name: inits[name](store, self.factory) for name in names
        }
        self._leader_elect = leader_elect
        self._elector: Optional[LeaderElector] = None
        self._identity = identity
        self._started = threading.Event()

    def get(self, name: str) -> Controller:
        return self.controllers[name]

    def start(self, wait: bool = True) -> None:
        if self._leader_elect:
            self._elector = LeaderElector(
                self.store,
                LeaderElectionConfig(
                    lock_name="kube-controller-manager",
                    identity=self._identity,
                    on_started_leading=self._start_controllers,
                ),
            )
            self._elector.run_in_thread()
        else:
            self._start_controllers()
        if wait:
            self._started.wait(timeout=10.0)

    def _start_controllers(self) -> None:
        self.factory.start()
        self.factory.wait_for_cache_sync()
        for c in self.controllers.values():
            c.run()
        # preexisting objects reach each controller via the informer
        # replay (handlers were registered in __init__, before start)
        self._started.set()

    def stop(self) -> None:
        for c in self.controllers.values():
            c.stop()
        if self._elector is not None:
            self._elector.stop()
        self.factory.stop()


__all__ = [
    "Controller",
    "ControllerManager",
    "DaemonSetController",
    "DeploymentController",
    "EndpointsController",
    "GarbageCollector",
    "JobController",
    "NodeLifecycleController",
    "PersistentVolumeController",
    "ReplicaSetController",
    "ReplicationController",
    "StatefulSetController",
    "new_controller_initializers",
]
