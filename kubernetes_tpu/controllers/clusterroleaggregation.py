"""ClusterRole aggregation controller (reference
``pkg/controller/clusterroleaggregation/clusterroleaggregation_
controller.go``): a ClusterRole with an aggregation rule gets its
``rules`` REPLACED by the union of all ClusterRoles matching any of its
label selectors — RBAC extensibility without editing the aggregate role
(how e.g. ``admin``/``edit``/``view`` absorb CRD roles upstream).
"""

from __future__ import annotations

from kubernetes_tpu.api.types import ClusterRole, PolicyRule
from kubernetes_tpu.controllers.base import Controller


def _rule_key(r: PolicyRule) -> tuple:
    return (
        tuple(sorted(r.verbs)), tuple(sorted(r.resources)),
        tuple(sorted(r.resource_names)),
        tuple(sorted(r.non_resource_urls)),
    )


class ClusterRoleAggregationController(Controller):
    name = "clusterrole-aggregation"

    def register(self) -> None:
        self.factory.informer_for("ClusterRole").add_event_handler(
            on_add=lambda r: self._enqueue_aggregates(),
            on_update=lambda o, n: self._enqueue_aggregates(),
            on_delete=lambda r: self._enqueue_aggregates(),
        )

    def _enqueue_aggregates(self) -> None:
        for role in self.store.list_cluster_roles():
            if role.aggregation_label_selectors:
                self.enqueue_key(role.name)

    def sync(self, key: str) -> None:
        role = self.store.get_cluster_role(key)
        if role is None or not role.aggregation_label_selectors:
            return
        union: dict = {}
        for candidate in sorted(self.store.list_cluster_roles(),
                                key=lambda r: r.name):
            if candidate.name == key:
                continue
            labels = candidate.metadata.labels
            if not any(
                all(labels.get(k) == v for k, v in sel.items())
                for sel in role.aggregation_label_selectors
            ):
                continue
            for rule in candidate.rules:
                union.setdefault(_rule_key(rule), rule)
        want = list(union.values())
        if [_rule_key(r) for r in role.rules] == \
                [_rule_key(r) for r in want]:
            return

        def mutate(r: ClusterRole) -> bool:
            r.rules = want
            return True

        self.store.mutate_object("ClusterRole", "", key, mutate)
