"""DaemonSet reconcile loop.

Behavioral equivalent of the reference's
``pkg/controller/daemon/daemon_controller.go``: one pod per eligible
node. Like post-1.12 upstream, the controller does not place pods itself
— it stamps each pod with a required node-affinity to its target node
(``metadata.name`` matchFields) plus the daemon tolerations, and the
default scheduler binds it (reference ``util/daemonset_util.go``
ReplaceDaemonSetPodNodeNameNodeAffinity).
"""

from __future__ import annotations

from kubernetes_tpu.api.types import DaemonSet, Node, Pod, WorkloadStatus
from kubernetes_tpu.controllers.base import (
    Controller,
    is_owned_by,
    owner_ref,
    split_key,
    with_status,
)


class DaemonSetController(Controller):
    name = "daemonset"

    def register(self) -> None:
        self.factory.informer_for("DaemonSet").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=self.enqueue,
        )
        self.factory.informer_for("Node").add_event_handler(
            on_add=lambda n: self._all_daemonsets(),
            # cordon/taint/uncordon arrive as node updates and change
            # daemon-pod eligibility (reference daemon controller's
            # updateNode path)
            on_update=lambda old, new: self._all_daemonsets(),
            on_delete=lambda n: self._all_daemonsets(),
        )
        self.factory.informer_for("Pod").add_event_handler(
            on_add=self._pod_changed,
            # binding arrives as MODIFIED; without it ready_replicas
            # would stay stale until an unrelated event
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_changed,
        )
        self.pod_lister = self.factory.lister_for("Pod")
        self.node_lister = self.factory.lister_for("Node")

    def _all_daemonsets(self) -> None:
        for ds in self.store.list_daemon_sets():
            self.enqueue(ds)

    def _pod_changed(self, pod: Pod) -> None:
        for r in pod.metadata.owner_references:
            if r.get("controller") and r.get("kind") == "DaemonSet":
                self.enqueue_key(f"{pod.namespace}/{r['name']}")

    def _eligible(self, ds: DaemonSet, node: Node) -> bool:
        if node.spec.unschedulable:
            return False
        tols = self._tolerations(ds)
        return all(
            taint.effect not in ("NoSchedule", "NoExecute")
            or any(t.tolerates(taint) for t in tols)
            for taint in node.spec.taints
        )

    @staticmethod
    def _tolerations(ds: DaemonSet):
        from kubernetes_tpu.api.types import Toleration

        spec = (ds.template or {}).get("spec", {})
        return [Toleration.from_dict(t) for t in (spec.get("tolerations") or [])]

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        ds = None
        for d in self.store.list_daemon_sets():
            if d.metadata.namespace == ns and d.metadata.name == name:
                ds = d
                break
        if ds is None:
            return
        owned = [
            p for p in self.pod_lister.by_namespace(ns)
            if is_owned_by(p, "DaemonSet", ds)
        ]
        by_node = {}
        for p in owned:
            by_node.setdefault(self._target_node(p), []).append(p)
        want_nodes = {
            n.name for n in self.node_lister.list() if self._eligible(ds, n)
        }
        for node_name in want_nodes:
            if not by_node.get(node_name):
                self._create_pod(ds, node_name)
        for node_name, pods in by_node.items():
            keep = 1 if node_name in want_nodes else 0
            for p in pods[keep:]:
                self.store.delete_pod(p.namespace, p.name)
        status = WorkloadStatus(
            replicas=len(want_nodes),
            ready_replicas=sum(
                1 for node, pods in by_node.items()
                if node in want_nodes and pods and pods[0].spec.node_name
            ),
        )
        if status != ds.status:
            self.store.add_daemon_set(with_status(ds, status))

    @staticmethod
    def _target_node(pod: Pod) -> str:
        if pod.spec.node_name:
            return pod.spec.node_name
        aff = pod.spec.affinity
        if aff and aff.node_affinity and \
                aff.node_affinity.required_during_scheduling_ignored_during_execution:
            for term in (aff.node_affinity
                         .required_during_scheduling_ignored_during_execution
                         .node_selector_terms):
                for req in term.match_fields:
                    if req.key == "metadata.name" and req.values:
                        return req.values[0]
        return ""

    def _create_pod(self, ds: DaemonSet, node_name: str) -> None:
        import json

        template = json.loads(json.dumps(ds.template or {}))
        spec = template.setdefault("spec", {})
        aff = spec.setdefault("affinity", {}).setdefault("nodeAffinity", {})
        aff["requiredDuringSchedulingIgnoredDuringExecution"] = {
            "nodeSelectorTerms": [{
                "matchFields": [{
                    "key": "metadata.name",
                    "operator": "In",
                    "values": [node_name],
                }],
            }],
        }
        pod = Pod.from_dict(template)
        pod.metadata.namespace = ds.metadata.namespace
        pod.metadata.name = f"{ds.metadata.name}-{node_name}-{pod.metadata.uid}"
        pod.metadata.owner_references = list(pod.metadata.owner_references) + [
            owner_ref("DaemonSet", ds)
        ]
        self.store.create_pod(pod)
