"""Attach/detach controller.

Behavioral equivalent of the reference's
``pkg/controller/volume/attachdetach`` (attach_detach_controller.go +
reconciler): maintain each node's ``status.volumesAttached`` — the PVs
that must be attached because a pod scheduled to the node mounts their
claim — and detach (remove) volumes whose last consumer left the node.

Like the reference, the controller keeps an incremental desired-state-
of-world (``pkg/controller/volume/attachdetach/cache``): pod and PVC
events update per-node maps in O(event) instead of rescanning the whole
pod table per sync, and a slow periodic resync rebuilds the DSW from
scratch as the backstop. Node writes go through the store's CAS mutate
loop so concurrent node-status writers (kubelet image GC, eviction)
never clobber this controller's fields, and a volume the kubelet still
reports in ``status.volumesInUse`` is NOT detached (the reference's
safe-detach interlock; its 6-minute force-detach timeout is out of
scope for this harness).
"""

from __future__ import annotations

import threading
from typing import Dict, Set

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.controllers.base import Controller


class AttachDetachController(Controller):
    name = "attachdetach"

    # DSW rebuild backstop (the reference reconciler loops every 100ms
    # against its cloud actuator; a slow resync suffices for
    # API-visible state)
    RESYNC_SECONDS = 30.0

    def register(self) -> None:
        self._dsw_lock = threading.Lock()
        # node -> pod key -> referenced claim keys ("ns/claim")
        self._dsw: Dict[str, Dict[str, Set[str]]] = {}
        # claim key -> node names with consumers (PVC-event fanout)
        self._claim_nodes: Dict[str, Set[str]] = {}
        self.factory.informer_for("Pod").add_event_handler(
            on_add=self._pod_upsert,
            on_update=lambda old, new: self._pod_update(old, new),
            on_delete=self._pod_delete,
        )
        # all three PVC transitions matter: a claim may arrive already
        # Bound (ADDED), re-bind (MODIFIED), or vanish (DELETED)
        self.factory.informer_for("PersistentVolumeClaim").add_event_handler(
            on_add=self._pvc_changed,
            on_update=lambda old, new: self._pvc_changed(new),
            on_delete=self._pvc_changed,
        )
        # a kubelet unmount report (volumesInUse shrinks) may unblock a
        # pending detach — don't wait for the resync backstop
        self.factory.informer_for("Node").add_event_handler(
            on_update=lambda old, new: (
                self.enqueue_key(new.name)
                if old is not None
                and old.status.volumes_in_use != new.status.volumes_in_use
                else None
            ),
        )

    # -- incremental DSW maintenance -----------------------------------
    @staticmethod
    def _claims_of(pod: Pod) -> Set[str]:
        return {
            f"{pod.namespace}/{v.persistent_volume_claim}"
            for v in pod.spec.volumes if v.persistent_volume_claim
        }

    def _pod_upsert(self, pod: Pod) -> None:
        if not pod.spec.node_name:
            return
        claims = self._claims_of(pod)
        node = pod.spec.node_name
        key = pod.full_name()
        with self._dsw_lock:
            if claims and pod.status.phase not in ("Succeeded", "Failed"):
                self._dsw.setdefault(node, {})[key] = claims
                for c in claims:
                    self._claim_nodes.setdefault(c, set()).add(node)
            else:
                self._dsw.get(node, {}).pop(key, None)
        self.enqueue_key(node)

    def _pod_update(self, old: Pod, new: Pod) -> None:
        if old is not None and old.spec.node_name and \
                old.spec.node_name != new.spec.node_name:
            self._pod_delete(old)
        self._pod_upsert(new)

    def _pod_delete(self, pod: Pod) -> None:
        if not pod.spec.node_name:
            return
        with self._dsw_lock:
            self._dsw.get(pod.spec.node_name, {}).pop(pod.full_name(), None)
        self.enqueue_key(pod.spec.node_name)

    def _pvc_changed(self, pvc) -> None:
        # (re)bound or deleted claim: refresh every node with a consumer
        key = f"{pvc.namespace}/{pvc.name}"
        with self._dsw_lock:
            nodes = list(self._claim_nodes.get(key, ()))
        for node in nodes:
            self.enqueue_key(node)

    def resync(self) -> None:
        """Rebuild the DSW from scratch (one O(pods) pass) and enqueue
        every node whose attach state could have drifted."""
        dsw: Dict[str, Dict[str, Set[str]]] = {}
        claim_nodes: Dict[str, Set[str]] = {}
        for p in self.store.list_pods():
            if not p.spec.node_name or \
                    p.status.phase in ("Succeeded", "Failed"):
                continue
            claims = self._claims_of(p)
            if not claims:
                continue
            dsw.setdefault(p.spec.node_name, {})[p.full_name()] = claims
            for c in claims:
                claim_nodes.setdefault(c, set()).add(p.spec.node_name)
        with self._dsw_lock:
            stale = set(self._dsw) | set(dsw)
            self._dsw = dsw
            self._claim_nodes = claim_nodes
        for node in stale:
            self.enqueue_key(node)

    # -- reconcile ------------------------------------------------------
    def _desired_attached(self, node_name: str) -> Set[str]:
        """PV names backing the node's consumed, BOUND claims."""
        with self._dsw_lock:
            claims = {
                c for per_pod in self._dsw.get(node_name, {}).values()
                for c in per_pod
            }
        wanted: Set[str] = set()
        for claim in claims:
            ns, _, name = claim.partition("/")
            pvc = self.store.get_pvc(ns, name)
            if pvc is not None and pvc.volume_name:
                wanted.add(pvc.volume_name)
        return wanted

    def sync(self, key: str) -> None:
        node = self.store.get_node(key)
        if node is None:
            with self._dsw_lock:
                self._dsw.pop(key, None)
            return
        wanted = self._desired_attached(key)

        def mutate(n) -> bool:
            attached = set(n.status.volumes_attached)
            # the kubelet's mount report is the safe-detach interlock:
            # a volume still in use stays attached even with no desired
            # consumer left
            in_use = set(n.status.volumes_in_use)
            new = sorted(wanted | (attached & in_use))
            if new == n.status.volumes_attached:
                return False
            n.status.volumes_attached = new
            return True

        self.store.mutate_object("Node", "", key, mutate)
