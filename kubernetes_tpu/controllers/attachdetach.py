"""Attach/detach controller.

Behavioral equivalent of the reference's
``pkg/controller/volume/attachdetach`` (attach_detach_controller.go +
reconciler): maintain each node's ``status.volumesAttached`` — the PVs
that must be attached because a pod scheduled to the node mounts their
claim — and detach (remove) volumes whose last consumer left the node.
The desired-state-of-world is recomputed from pods+PVCs per sync (the
reference builds the same DSW from the informer caches; its actuation
talks to cloud APIs, ours ends at the API-visible attach state, which is
what the scheduler's volume plugins and operators consume).
"""

from __future__ import annotations

from typing import Set

from kubernetes_tpu.api.types import Pod, shallow_copy
from kubernetes_tpu.controllers.base import Controller


class AttachDetachController(Controller):
    name = "attachdetach"

    # reconciler backstop (the reference reconciler loops every 100ms
    # against its cloud actuator; a slow periodic resync suffices for
    # API-visible state)
    RESYNC_SECONDS = 30.0

    def register(self) -> None:
        self.factory.informer_for("Pod").add_event_handler(
            on_add=self._pod_changed,
            on_update=lambda old, new: (self._pod_changed(old),
                                        self._pod_changed(new)),
            on_delete=self._pod_changed,
        )
        # all three PVC transitions matter: a claim may arrive already
        # Bound (ADDED), re-bind (MODIFIED), or vanish (DELETED)
        self.factory.informer_for("PersistentVolumeClaim").add_event_handler(
            on_add=self._pvc_changed,
            on_update=lambda old, new: self._pvc_changed(new),
            on_delete=self._pvc_changed,
        )
        self.pod_lister = self.factory.lister_for("Pod")

    def resync(self) -> None:
        for n in self.store.list_nodes():
            self.enqueue_key(n.name)

    def _pod_changed(self, pod: Pod) -> None:
        if pod.spec.node_name:
            self.enqueue_key(pod.spec.node_name)

    def _pvc_changed(self, pvc) -> None:
        # (re)bound claim: every node running one of its consumers
        # needs its attach state refreshed
        for p in self.pod_lister.by_namespace(pvc.namespace):
            if not p.spec.node_name:
                continue
            if any(v.persistent_volume_claim == pvc.name
                   for v in p.spec.volumes):
                self.enqueue_key(p.spec.node_name)

    def _desired_attached(self, node_name: str) -> Set[str]:
        """PV names any non-terminal pod on the node mounts via a bound
        claim (the desired state of world)."""
        wanted: Set[str] = set()
        for p in self.store.list_pods():
            if p.spec.node_name != node_name:
                continue
            if p.status.phase in ("Succeeded", "Failed"):
                continue
            for vol in p.spec.volumes:
                if not vol.persistent_volume_claim:
                    continue
                pvc = self.store.get_pvc(p.namespace,
                                         vol.persistent_volume_claim)
                if pvc is not None and pvc.volume_name:
                    wanted.add(pvc.volume_name)
        return wanted

    def sync(self, key: str) -> None:
        node = self.store.get_node(key)
        if node is None:
            return
        wanted = sorted(self._desired_attached(key))
        if node.status.volumes_attached == wanted:
            return
        updated = shallow_copy(node)
        updated.metadata = shallow_copy(node.metadata)
        updated.status = shallow_copy(node.status)
        updated.status.volumes_attached = wanted
        # volumes_in_use is the KUBELET's mount report (the safety
        # interlock against premature detach) — not this controller's
        # to write
        self.store.update_node(updated)
