"""Endpoints reconcile loop.

Behavioral equivalent of the reference's
``pkg/controller/endpoint/endpoints_controller.go``: for every Service,
maintain an Endpoints object listing the addresses of ready bound pods
matching the service selector (kube-proxy's input).
"""

from __future__ import annotations

from kubernetes_tpu.api.types import (
    FAILED,
    SUCCEEDED,
    EndpointAddress,
    Endpoints,
    Pod,
    Service,
)
from kubernetes_tpu.controllers.base import Controller, split_key


class EndpointsController(Controller):
    name = "endpoints"

    def register(self) -> None:
        self.factory.informer_for("Service").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=self.enqueue,
        )
        self.factory.informer_for("Pod").add_event_handler(
            on_add=self._pod_changed,
            # both sides: a label change must resync the service the pod
            # LEFT as well as the one it joined
            on_update=lambda old, new: (self._pod_changed(old),
                                        self._pod_changed(new)),
            on_delete=self._pod_changed,
        )
        self.pod_lister = self.factory.lister_for("Pod")
        self.svc_lister = self.factory.lister_for("Service")

    def _pod_changed(self, pod: Pod) -> None:
        for svc in self.svc_lister.by_namespace(pod.namespace):
            if self._selects(svc, pod):
                self.enqueue(svc)

    @staticmethod
    def _selects(svc: Service, pod: Pod) -> bool:
        if not svc.selector:
            return False
        return all(
            pod.metadata.labels.get(k) == v for k, v in svc.selector.items()
        )

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        svc = None
        for s in self.store.list_all_services():
            if s.metadata.namespace == ns and s.metadata.name == name:
                svc = s
                break
        if svc is None:
            self.store.delete_endpoints(ns, name)
            return
        addresses = []
        for pod in self.pod_lister.by_namespace(ns):
            if not self._selects(svc, pod):
                continue
            if not pod.spec.node_name or pod.status.phase in (SUCCEEDED, FAILED):
                continue
            if pod.metadata.deletion_timestamp is not None:
                continue
            addresses.append(EndpointAddress(
                ip=pod.status.pod_ip or pod.full_name(),
                node_name=pod.spec.node_name,
                target_pod=pod.full_name(),
            ))
        ep = Endpoints(addresses=sorted(addresses, key=lambda a: a.target_pod),
                       ports=list(svc.ports))
        ep.metadata.name = name
        ep.metadata.namespace = ns
        # skip the no-op write: an unconditional upsert would bump the
        # resourceVersion and fan a spurious MODIFIED to every watcher on
        # each pod event per selecting service
        old = self.store.get_endpoints(ns, name)
        if old is not None and old.addresses == ep.addresses and old.ports == ep.ports:
            return
        self.store.upsert_endpoints(ep)
