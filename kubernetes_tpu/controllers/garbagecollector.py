"""Garbage collector: ownerReference cascade deletion.

Behavioral equivalent of the reference's
``pkg/controller/garbagecollector/garbagecollector.go``: maintains a
dependency graph of ownerReferences and deletes dependents whose
(controller) owners no longer exist. The reference scans on watch deltas;
here owner deletes enqueue their dependents directly plus a periodic full
sweep catches orphans created while the collector was down.
"""

from __future__ import annotations

import threading

from kubernetes_tpu.controllers.base import Controller

# kinds that can own other objects, with their store list accessors
_OWNER_KINDS = {
    "ReplicaSet": "list_all_replica_sets",
    "ReplicationController": "list_all_replication_controllers",
    "StatefulSet": "list_all_stateful_sets",
    "Deployment": "list_deployments",
    "DaemonSet": "list_daemon_sets",
    "Job": "list_jobs",
}

class GarbageCollector(Controller):
    name = "garbagecollector"
    sweep_interval = 5.0

    def register(self) -> None:
        for kind in _OWNER_KINDS:
            self.factory.informer_for(kind).add_event_handler(
                on_delete=lambda obj, kind=kind: self.enqueue_key("sweep"),
            )
        self.pod_lister = self.factory.lister_for("Pod")
        self._sweep_stop = threading.Event()

    def run(self) -> None:
        super().run()
        t = threading.Thread(target=self._sweep_loop, daemon=True,
                             name="gc-sweeper")
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._sweep_stop.set()
        super().stop()

    def _sweep_loop(self) -> None:
        while not self._sweep_stop.wait(self.sweep_interval):
            self.enqueue_key("sweep")

    def sync(self, key: str) -> None:
        # runtime-registered (CRD) kinds join the graph on both sides:
        # their instances can own and be owned (the reference GC is
        # fully generic over discovered resources,
        # garbagecollector.go Sync/resyncMonitors)
        custom_kinds = list(getattr(self.store, "custom_kind_names",
                                    list)())
        live_uids = set()
        for list_name in _OWNER_KINDS.values():
            for obj in getattr(self.store, list_name)():
                live_uids.add(obj.metadata.uid)
        for kind in custom_kinds:
            for obj in self.store.list_objects(kind):
                live_uids.add(obj.metadata.uid)
        # dependents: pods owned by a controller that no longer exists.
        # Only kinds we track count as "absent"; an owner of an untracked
        # kind can't be proven dead, so its dependents are left alone
        # (upstream GC deletes only when the referenced object is
        # actually verified absent).
        tracked = set(_OWNER_KINDS) | set(custom_kinds)
        for pod in self.pod_lister.list():
            for ref in pod.metadata.owner_references:
                if (
                    ref.get("controller")
                    and ref.get("kind") in tracked
                    and ref.get("uid") not in live_uids
                ):
                    self.store.delete_pod(pod.namespace, pod.name)
                    break
        # second-level: ReplicaSets owned by a vanished Deployment
        for rs in self.store.list_all_replica_sets():
            for ref in rs.metadata.owner_references:
                if (
                    ref.get("controller")
                    and ref.get("kind") in tracked
                    and ref.get("uid") not in live_uids
                ):
                    self.store.delete_replica_set(rs.namespace, rs.name)
                    break
        # custom instances owned by a vanished owner (typed or custom)
        for kind in custom_kinds:
            for obj in self.store.list_objects(kind):
                for ref in obj.metadata.owner_references:
                    if (
                        ref.get("controller")
                        and ref.get("kind") in tracked
                        and ref.get("uid") not in live_uids
                    ):
                        self.store.delete_object(
                            kind, obj.metadata.namespace,
                            obj.metadata.name,
                        )
                        break
