"""StatefulSet reconcile loop.

Behavioral equivalent of the reference's
``pkg/controller/statefulset/stateful_set_control.go``: pods are named
``{set}-{ordinal}`` and created in ordinal order, each waiting for its
predecessor to be running-and-ready before the next is created; scale-down
removes the highest ordinal first. "Ready" here is bound-or-running —
in harness clusters without kubelets, binding is the finish line
(SURVEY.md section 3.5); with hollow kubelets it means Running.
"""

from __future__ import annotations

from kubernetes_tpu.api.types import RUNNING, Pod, StatefulSet, WorkloadStatus
from kubernetes_tpu.controllers.base import (
    Controller,
    owner_ref,
    split_key,
    with_status,
)


def _ready(pod: Pod) -> bool:
    return bool(pod.spec.node_name) or pod.status.phase == RUNNING


class StatefulSetController(Controller):
    name = "statefulset"

    def register(self) -> None:
        self.factory.informer_for("StatefulSet").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=self.enqueue,
        )
        self.factory.informer_for("Pod").add_event_handler(
            on_add=self._pod_changed,
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_changed,
        )
        self.pod_lister = self.factory.lister_for("Pod")

    def _pod_changed(self, pod: Pod) -> None:
        for r in pod.metadata.owner_references:
            if r.get("controller") and r.get("kind") == "StatefulSet":
                self.enqueue_key(f"{pod.namespace}/{r['name']}")

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        sset = None
        for s in self.store.list_all_stateful_sets():
            if s.metadata.namespace == ns and s.metadata.name == name:
                sset = s
                break
        if sset is None:
            return
        # ordinal -> pod, in one list pass (names are deterministic
        # "{name}-{ordinal}"). A full listing also finds higher ordinals
        # stranded behind a gap after a scale-down race, which a scan
        # stopping at the first missing ordinal would leak forever.
        prefix = f"{name}-"
        pods = {i: None for i in range(max(sset.replicas, 0))}
        for p in self.store.list_pods(ns):
            if not p.name.startswith(prefix):
                continue
            suffix = p.name[len(prefix):]
            if not suffix.isdigit():
                continue
            refs = p.metadata.owner_references
            if refs and not any(
                r.get("kind") == "StatefulSet" and r.get("name") == name
                for r in refs
            ):
                continue  # same name prefix, different owner
            pods[int(suffix)] = p
        existing = [i for i, p in pods.items() if p is not None]
        # scale down: delete highest ordinal first, one at a time
        if existing and max(existing) >= sset.replicas:
            top = max(existing)
            self.store.delete_pod(ns, f"{name}-{top}")
            status = WorkloadStatus(replicas=len(existing) - 1,
                                    ready_replicas=sset.status.ready_replicas)
            if status != sset.status:
                self.store.add_stateful_set(with_status(sset, status))
            return
        # scale up: create the first missing ordinal, only if all
        # predecessors are ready (OrderedReady pod management)
        for i in range(sset.replicas):
            p = pods.get(i)
            if p is None:
                self._create_pod(sset, i)
                break
            if not _ready(p):
                break  # wait for predecessor
        live = [p for p in pods.values() if p is not None]
        status = WorkloadStatus(
            replicas=len(live),
            ready_replicas=sum(1 for p in live if _ready(p)),
        )
        if status != sset.status:
            self.store.add_stateful_set(with_status(sset, status))

    def _create_pod(self, sset: StatefulSet, ordinal: int) -> None:
        pod = Pod.from_dict(dict(sset.template or {}))
        pod.metadata.namespace = sset.metadata.namespace
        pod.metadata.name = f"{sset.metadata.name}-{ordinal}"
        pod.metadata.owner_references = list(pod.metadata.owner_references) + [
            owner_ref("StatefulSet", sset)
        ]
        self.store.create_pod(pod)
