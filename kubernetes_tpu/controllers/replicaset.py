"""ReplicaSet / ReplicationController reconcile loops.

Behavioral equivalent of the reference's
``pkg/controller/replicaset/replica_set.go`` (syncReplicaSet: list owned
pods via selector, diff against ``spec.replicas``, create/delete the
difference) — RC is the same loop over the older kind, exactly as upstream
implements RC by wrapping the RS controller
(``pkg/controller/replication/replication_controller.go``).
"""

from __future__ import annotations

from kubernetes_tpu.api.labels import LabelSelector
from kubernetes_tpu.api.types import (
    FAILED,
    SUCCEEDED,
    Pod,
    ReplicaSet,
    ReplicationController,
)
from kubernetes_tpu.controllers.base import (
    Controller,
    controller_of,
    is_owned_by,
    owner_ref,
    split_key,
    with_status,
)
from kubernetes_tpu.api.types import WorkloadStatus


def _is_active(pod: Pod) -> bool:
    """Active = not terminal and not being deleted (reference
    controller.FilterActivePods)."""
    return (
        pod.status.phase not in (SUCCEEDED, FAILED)
        and pod.metadata.deletion_timestamp is None
    )


class _ReplicaWorkloadController(Controller):
    """Shared loop; subclasses define the kind + accessor surface."""

    kind = ""

    def register(self) -> None:
        inf = self.factory.informer_for(self.kind)
        inf.add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=self.enqueue,
        )
        pods = self.factory.informer_for("Pod")
        pods.add_event_handler(
            on_add=self._pod_changed,
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_changed,
        )
        self.pod_lister = self.factory.lister_for("Pod")
        self.lister = self.factory.lister_for(self.kind)

    def _pod_changed(self, pod: Pod) -> None:
        ref = controller_of(pod)
        if ref is not None and ref.get("kind") == self.kind:
            self.enqueue_key(f"{pod.namespace}/{ref['name']}")

    # -- kind-specific hooks -------------------------------------------
    def _get(self, namespace: str, name: str):
        raise NotImplementedError

    def _selector_matches(self, owner, pod: Pod) -> bool:
        raise NotImplementedError

    def _update_status(self, owner) -> None:
        raise NotImplementedError

    # ------------------------------------------------------------------
    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        owner = self._get(ns, name)
        if owner is None:
            return
        owned, orphans = [], []
        for p in self.pod_lister.by_namespace(ns):
            if is_owned_by(p, self.kind, owner):
                owned.append(p)
            elif controller_of(p) is None and self._selector_matches(owner, p):
                orphans.append(p)
        # adopt matching orphans (reference ClaimPods/AdoptPod) so their
        # future events route back to this controller
        for p in orphans:
            adopted = self._adopt(p, owner)
            owned.append(adopted)
        pods = owned
        active = [p for p in pods if _is_active(p)]
        diff = owner.replicas - len(active)
        if diff > 0:
            for i in range(diff):
                self._create_pod(owner, len(pods) + i)
        elif diff < 0:
            # victims: prefer unassigned, then newest (reference
            # ActivePods sort, controller_utils.go)
            victims = sorted(
                active,
                key=lambda p: (bool(p.spec.node_name),
                               -p.metadata.creation_timestamp),
            )[: -diff]
            for p in victims:
                self.store.delete_pod(p.namespace, p.name)
        status = WorkloadStatus(
            replicas=len(active) + max(diff, 0),
            ready_replicas=sum(1 for p in active if p.spec.node_name),
        )
        # only write when observed state changed — an unconditional write
        # would MODIFY-event this controller into a hot reconcile loop
        if status != owner.status:
            self._update_status(with_status(owner, status))

    def _adopt(self, pod: Pod, owner) -> Pod:
        import copy

        adopted = copy.copy(pod)
        adopted.metadata = copy.copy(pod.metadata)
        adopted.metadata.owner_references = list(pod.metadata.owner_references) + [
            owner_ref(self.kind, owner)
        ]
        self.store.update_pod(adopted)
        return adopted

    def _create_pod(self, owner, ordinal: int) -> None:
        template = dict(owner.template or {})
        pod = Pod.from_dict(template)
        pod.metadata.namespace = owner.metadata.namespace
        base = template.get("metadata", {}).get("generateName") or \
            f"{owner.metadata.name}-"
        pod.metadata.name = f"{base}{pod.metadata.uid}"
        pod.metadata.owner_references = list(pod.metadata.owner_references) + [
            owner_ref(self.kind, owner)
        ]
        self.store.create_pod(pod)


class ReplicaSetController(_ReplicaWorkloadController):
    name = "replicaset"
    kind = "ReplicaSet"

    def _get(self, namespace: str, name: str):
        return self.store.get_replica_set(namespace, name)

    def _selector_matches(self, rs: ReplicaSet, pod: Pod) -> bool:
        if rs.selector is None:
            return False
        return rs.selector.to_selector().matches(pod.metadata.labels)

    def _update_status(self, rs: ReplicaSet) -> None:
        self.store.update_replica_set(rs)


class ReplicationController(_ReplicaWorkloadController):  # noqa: N801 — k8s kind name
    name = "replicationcontroller"
    kind = "ReplicationController"

    def _get(self, namespace: str, name: str):
        for rc in self.store.list_all_replication_controllers():
            if rc.metadata.namespace == namespace and rc.metadata.name == name:
                return rc
        return None

    def _selector_matches(self, rc, pod: Pod) -> bool:
        if not rc.selector:
            return False
        return LabelSelector(match_labels=dict(rc.selector)) \
            .to_selector().matches(pod.metadata.labels)

    def _update_status(self, rc) -> None:
        self.store.add_replication_controller(rc)
