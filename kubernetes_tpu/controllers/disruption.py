"""Disruption controller: maintains PodDisruptionBudget status.

Behavioral equivalent of the reference's ``pkg/controller/disruption/
disruption.go`` (DisruptionController.trySync → updatePdbStatus): for
every PDB, count the currently-healthy matching pods, derive the desired
healthy count from ``minAvailable`` / ``maxUnavailable`` (absolute or
percentage — percentages resolve against the expected pod count taken
from the owning controllers' desired replicas, reference
``getExpectedPodCount``/``getExpectedScale``), and publish
``status.disruptionsAllowed = currentHealthy − desiredHealthy`` — the
number the eviction API and scheduler preemption consult live.
"""

from __future__ import annotations

import math
from typing import List

from kubernetes_tpu.api.types import Pod, PodDisruptionBudgetStatus, shallow_copy
from kubernetes_tpu.controllers.base import Controller, controller_of, split_key


def _parse_percent(value) -> float:
    """"30%" -> 0.30 (raises on malformed)."""
    return float(str(value).rstrip("%")) / 100.0


def _is_percent(value) -> bool:
    return isinstance(value, str) and value.endswith("%")


class DisruptionController(Controller):
    name = "disruption"

    def register(self) -> None:
        self.factory.informer_for("PodDisruptionBudget").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=self.enqueue,
        )
        self.factory.informer_for("Pod").add_event_handler(
            on_add=self._pod_changed,
            on_update=lambda old, new: (self._pod_changed(old),
                                        self._pod_changed(new)),
            on_delete=self._pod_changed,
        )
        self.pod_lister = self.factory.lister_for("Pod")
        self.pdb_lister = self.factory.lister_for("PodDisruptionBudget")

    def _pod_changed(self, pod: Pod) -> None:
        # reference getPdbForPod: re-sync every PDB whose selector
        # matches the changed pod
        for pdb in self.pdb_lister.by_namespace(pod.namespace):
            if pdb.selector.matches(pod.metadata.labels):
                self.enqueue(pdb)

    # ------------------------------------------------------------------
    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pdb = self.store.get_object("PodDisruptionBudget", ns, name)
        if pdb is None:
            return
        pods = [
            p for p in self.pod_lister.by_namespace(ns)
            if pdb.selector.matches(p.metadata.labels)
        ]
        current_healthy = sum(1 for p in pods if self._healthy(p))
        expected, desired = self._expected_and_desired(pdb, pods)
        if expected is None:
            # fail CLOSED (reference getExpectedScale error -> failSafe
            # sets DisruptionsAllowed=0): an unresolvable owner must
            # block disruptions, never inflate the budget
            expected, desired = len(pods), current_healthy
        allowed = max(0, current_healthy - desired)
        new_status = PodDisruptionBudgetStatus(
            disruptions_allowed=allowed,
            current_healthy=current_healthy,
            desired_healthy=desired,
            expected_pods=expected,
        )
        if new_status == pdb.status:
            return
        updated = shallow_copy(pdb)
        updated.metadata = shallow_copy(pdb.metadata)
        updated.status = new_status
        self.store.update_object("PodDisruptionBudget", updated)

    @staticmethod
    def _healthy(pod: Pod) -> bool:
        """Reference counts pods with Ready condition; in this harness a
        bound, non-terminating pod is the running/ready analog
        (scheduler_perf semantics: binding is the finish line)."""
        return bool(pod.spec.node_name) and \
            pod.metadata.deletion_timestamp is None

    def _expected_and_desired(self, pdb, pods: List[Pod]):
        """(expectedPods, desiredHealthy) — disruption.go
        getExpectedPodCount: percentages (and maxUnavailable) resolve
        against the owning controllers' desired scale; absolute
        minAvailable uses the matching-pod count."""
        if pdb.max_unavailable is not None or (
            pdb.min_available is not None and _is_percent(pdb.min_available)
        ):
            expected = self._expected_scale(pods)
            if expected is None:
                return None, None
        else:
            expected = len(pods)
        if pdb.max_unavailable is not None:
            mu = pdb.max_unavailable
            # percentages round UP (reference GetScaledValueFromIntOrPercent
            # with roundUp=true): 30% of 7 allows 3 unavailable, not 2
            unavail = (
                math.ceil(_parse_percent(mu) * expected)
                if _is_percent(mu) else int(mu)
            )
            return expected, max(0, expected - unavail)
        if pdb.min_available is None:
            return expected, 0
        ma = pdb.min_available
        if _is_percent(ma):
            return expected, math.ceil(_parse_percent(ma) * expected)
        return expected, int(ma)

    def _expected_scale(self, pods: List[Pod]):
        """Sum of the owning workload controllers' desired replicas;
        bare pods count themselves. Returns None when any owner cannot
        be resolved — the caller fails CLOSED (disruption.go
        getExpectedScale returns an error there)."""
        seen = set()
        total = 0
        bare = 0
        for pod in pods:
            ref = controller_of(pod)
            if ref is None:
                bare += 1
                continue
            uid = ref.get("uid")
            if uid in seen:
                continue
            seen.add(uid)
            owner = self._find_owner(ref, pod.namespace)
            if owner is None:
                return None
            total += owner
        return total + bare

    def _find_owner(self, ref: dict, namespace: str):
        kind = ref.get("kind")
        name = ref.get("name")
        getters = {
            "ReplicaSet": self.store.get_replica_set,
            "Job": self.store.get_job,
        }
        if kind in getters:
            obj = getters[kind](namespace, name)
        else:
            try:
                obj = self.store.get_object(kind, namespace, name)
            except KeyError:
                return None
        if obj is None:
            return None
        replicas = getattr(getattr(obj, "spec", None), "replicas", None)
        if replicas is None:
            replicas = getattr(obj, "replicas", None)
        return int(replicas) if replicas is not None else None
