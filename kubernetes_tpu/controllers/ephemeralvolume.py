"""Ephemeral-volume controller (reference ``pkg/controller/volume/
ephemeral/controller.go``): a pod volume with ``ephemeral`` set implies
a PVC named ``<pod>-<volume>`` owned by the pod; this loop creates the
claim when absent (the owner reference makes the GC reclaim it with the
pod — controller.go handleVolume/podWork).
"""

from __future__ import annotations

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import ObjectMeta, PersistentVolumeClaim
from kubernetes_tpu.controllers.base import Controller, owner_ref, split_key


class EphemeralVolumeController(Controller):
    name = "ephemeral-volume"

    def register(self) -> None:
        self.factory.informer_for("Pod").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
        )

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pod = self.store.get_pod(ns, name)
        if pod is None or pod.metadata.deletion_timestamp is not None:
            return
        for vol in pod.spec.volumes:
            if not vol.ephemeral:
                continue
            claim_name = f"{name}-{vol.name}"
            if self.store.get_pvc(ns, claim_name) is not None:
                # controller.go: an existing claim NOT owned by this pod
                # is a conflict the controller reports and leaves alone;
                # either way there is nothing to create
                continue
            self.store.add_pvc(PersistentVolumeClaim(
                metadata=ObjectMeta(
                    name=claim_name, namespace=ns,
                    owner_references=[owner_ref("Pod", pod)],
                ),
                requests={"storage": parse_quantity("1Gi")},
                phase="Pending",
            ))
