"""CronJob controller.

Behavioral equivalent of the reference's ``pkg/controller/cronjob``
(cronjob_controller.go syncAll/syncOne): every CronJob whose 5-field
cron schedule has fired since its ``last_schedule_time`` gets a Job
created (named ``<cronjob>-<scheduled-unix-minute>``, owner-referenced),
and ``last_schedule_time`` advances. The reference polls every 10s
(``cronjob_controller.go: wait.Until(jm.syncAll, 10*time.Second)``);
this loop ticks faster so tests don't wait wall-clock minutes, and the
tick interval is injectable for the same reason.
"""

from __future__ import annotations

import time
from typing import Optional

from kubernetes_tpu.api.types import CronJob, Job, ObjectMeta, shallow_copy
from kubernetes_tpu.controllers.base import Controller, owner_ref, split_key


def cron_field_matches(field: str, value: int) -> bool:
    """One 5-field cron term: ``*``, ``*/n``, ``a``, ``a,b,c``, ``a-b``."""
    for part in field.split(","):
        if part == "*":
            return True
        if part.startswith("*/"):
            try:
                step = int(part[2:])
            except ValueError:
                continue
            if step > 0 and value % step == 0:
                return True
        elif "-" in part:
            try:
                lo, hi = (int(x) for x in part.split("-", 1))
            except ValueError:
                continue
            if lo <= value <= hi:
                return True
        else:
            try:
                if int(part) == value:
                    return True
            except ValueError:
                continue
    return False


def cron_matches(schedule: str, t: float) -> bool:
    """Does the 5-field ``schedule`` fire at time ``t`` (minute
    resolution)?"""
    fields = schedule.split()
    if len(fields) != 5:
        return False
    tm = time.localtime(t)
    # cron DOW is Sunday=0; Python tm_wday is Monday=0
    values = (tm.tm_min, tm.tm_hour, tm.tm_mday, tm.tm_mon,
              (tm.tm_wday + 1) % 7)
    return all(cron_field_matches(f, v) for f, v in zip(fields, values))


def next_fire_after(schedule: str, after: float,
                    horizon_minutes: int = 24 * 60) -> Optional[float]:
    """The first minute boundary > ``after`` where the schedule fires
    (bounded scan, like the reference's getRecentUnmetScheduleTimes)."""
    t = (int(after) // 60 + 1) * 60
    for _ in range(horizon_minutes):
        if cron_matches(schedule, t):
            return float(t)
        t += 60
    return None


class CronJobController(Controller):
    name = "cronjob"

    # injectable for tests (the reference uses a 10s resync)
    RESYNC_SECONDS = 1.0

    def register(self) -> None:
        self.factory.informer_for("CronJob").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
        )

    def resync(self) -> None:
        for cj in self.store.list_cron_jobs():
            self.enqueue(cj)

    def now(self) -> float:
        return time.time()

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        cj = self.store.get_cron_job(ns, name)
        if cj is None or cj.suspend:
            return
        now = self.now()
        anchor = cj.last_schedule_time or cj.metadata.creation_timestamp \
            or now
        due = next_fire_after(cj.schedule, anchor)
        if due is None or due > now:
            return
        # only the MOST RECENT unmet fire runs (reference syncOne takes
        # the latest of getRecentUnmetScheduleTimes and refuses a >100
        # backlog); catching up one-per-pass would burst a day of missed
        # "* * * * *" fires into ~1440 Jobs on resume
        while True:
            nxt = next_fire_after(cj.schedule, due)
            if nxt is None or nxt > now:
                break
            due = nxt
        job_name = f"{name}-{int(due) // 60}"
        if self.store.get_job(ns, job_name) is None:
            self.store.add_job(Job(
                metadata=ObjectMeta(
                    name=job_name, namespace=ns,
                    owner_references=[owner_ref("CronJob", cj)],
                ),
                completions=cj.completions,
                parallelism=cj.parallelism,
                template=dict(cj.job_template or {}),
                ttl_seconds_after_finished=cj.ttl_seconds_after_finished,
            ))
        updated = shallow_copy(cj)
        updated.last_schedule_time = due
        self.store.add_cron_job(updated)
