"""CronJob controller.

Behavioral equivalent of the reference's ``pkg/controller/cronjob``
(cronjob_controller.go syncAll/syncOne): every CronJob whose 5-field
cron schedule has fired since its ``last_schedule_time`` gets a Job
created (named ``<cronjob>-<scheduled-unix-minute>``, owner-referenced),
and ``last_schedule_time`` advances. The reference polls every 10s
(``cronjob_controller.go: wait.Until(jm.syncAll, 10*time.Second)``);
this loop ticks faster so tests don't wait wall-clock minutes, and the
tick interval is injectable for the same reason.
"""

from __future__ import annotations

import time
from typing import Optional

from kubernetes_tpu.api.types import CronJob, Job, ObjectMeta, shallow_copy
from kubernetes_tpu.controllers.base import Controller, owner_ref, split_key


def cron_field_matches(field: str, value: int) -> bool:
    """One 5-field cron term: ``*``, ``*/n``, ``a``, ``a,b,c``, ``a-b``,
    ``a-b/n`` (stepped range, standard cron)."""
    for part in field.split(","):
        if part == "*":
            return True
        step = 1
        if "/" in part:
            part, _, step_s = part.partition("/")
            try:
                step = int(step_s)
            except ValueError:
                continue
            if step <= 0:
                continue
        if part == "*":
            if value % step == 0:
                return True
        elif "-" in part:
            try:
                lo, hi = (int(x) for x in part.split("-", 1))
            except ValueError:
                continue
            if lo <= value <= hi and (value - lo) % step == 0:
                return True
        else:
            try:
                lo = int(part)
            except ValueError:
                continue
            # "a/n" behaves as "a-max/n" in standard cron; without a
            # range a bare value with a step only matches the value
            # itself when step is 1 (robfig/cron, the reference's
            # library, rejects bare-value steps — match conservatively)
            if lo == value:
                return True
    return False


def cron_matches(schedule: str, t: float) -> bool:
    """Does the 5-field ``schedule`` fire at time ``t`` (minute
    resolution)? Standard cron (and the reference's robfig/cron): when
    BOTH day-of-month and day-of-week are restricted (neither is
    ``*``), they are ORed — '0 0 13 * 5' fires on the 13th OR any
    Friday, not only Friday-the-13th."""
    fields = schedule.split()
    if len(fields) != 5:
        return False
    tm = time.localtime(t)
    # cron DOW is Sunday=0; Python tm_wday is Monday=0
    dow = (tm.tm_wday + 1) % 7
    if not cron_field_matches(fields[0], tm.tm_min):
        return False
    if not cron_field_matches(fields[1], tm.tm_hour):
        return False
    if not cron_field_matches(fields[3], tm.tm_mon):
        return False
    dom_field, dow_field = fields[2], fields[4]
    # vixie-cron rule: a field counts as restricted iff it does not
    # start with '*' ("*/2" is still unrestricted for the OR rule)
    dom_restricted = not dom_field.startswith("*")
    dow_restricted = not dow_field.startswith("*")
    dom_ok = cron_field_matches(dom_field, tm.tm_mday)
    dow_ok = cron_field_matches(dow_field, dow)
    if dom_restricted and dow_restricted:
        return dom_ok or dow_ok
    return dom_ok and dow_ok


def next_fire_after(schedule: str, after: float,
                    horizon_minutes: int = 24 * 60) -> Optional[float]:
    """The first minute boundary > ``after`` where the schedule fires
    (bounded scan, like the reference's getRecentUnmetScheduleTimes)."""
    t = (int(after) // 60 + 1) * 60
    for _ in range(horizon_minutes):
        if cron_matches(schedule, t):
            return float(t)
        t += 60
    return None


class CronJobController(Controller):
    name = "cronjob"

    # injectable for tests (the reference uses a 10s resync)
    RESYNC_SECONDS = 1.0

    def register(self) -> None:
        self.factory.informer_for("CronJob").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
        )

    def resync(self) -> None:
        for cj in self.store.list_cron_jobs():
            self.enqueue(cj)

    def now(self) -> float:
        return time.time()

    def _active_jobs(self, ns: str, cj: CronJob):
        """Unfinished Jobs owned by this CronJob (syncOne's activeList)."""
        out = []
        for job in self.store.list_jobs():
            if job.namespace != ns:
                continue
            if not any(
                r.get("kind") == "CronJob"
                and r.get("uid") == cj.metadata.uid
                for r in job.metadata.owner_references
            ):
                continue
            finished = (
                job.status.succeeded >= job.completions
                or job.status.failed > 0
            )
            if not finished:
                out.append(job)
        return out

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        cj = self.store.get_cron_job(ns, name)
        if cj is None or cj.suspend:
            return
        now = self.now()
        anchor = cj.last_schedule_time or cj.metadata.creation_timestamp \
            or now
        due = next_fire_after(cj.schedule, anchor)
        if due is None or due > now:
            return
        # only the MOST RECENT unmet fire runs (reference syncOne takes
        # the latest of getRecentUnmetScheduleTimes and refuses a >100
        # backlog); catching up one-per-pass would burst a day of missed
        # "* * * * *" fires into ~1440 Jobs on resume
        while True:
            nxt = next_fire_after(cj.schedule, due)
            if nxt is None or nxt > now:
                break
            due = nxt
        # startingDeadlineSeconds (cronjob/utils.go earliestTime clamp +
        # syncOne "Missed starting window"): a fire older than the
        # deadline is skipped — last_schedule_time still advances so the
        # stale fire never retries
        if cj.starting_deadline_seconds is not None and \
                now - due > cj.starting_deadline_seconds:
            updated = shallow_copy(cj)
            updated.last_schedule_time = due
            self.store.add_cron_job(updated)
            return
        # concurrencyPolicy (syncOne): Forbid skips the fire while a
        # previous Job still runs (WITHOUT advancing last_schedule_time,
        # so the fire retries until it runs or falls past the deadline);
        # Replace deletes the running Jobs first
        if cj.concurrency_policy in ("Forbid", "Replace"):
            active = self._active_jobs(ns, cj)
            if active and cj.concurrency_policy == "Forbid":
                return
            for job in active:
                self.store.delete_object("Job", ns, job.name)
        job_name = f"{name}-{int(due) // 60}"
        if self.store.get_job(ns, job_name) is None:
            self.store.add_job(Job(
                metadata=ObjectMeta(
                    name=job_name, namespace=ns,
                    owner_references=[owner_ref("CronJob", cj)],
                ),
                completions=cj.completions,
                parallelism=cj.parallelism,
                template=dict(cj.job_template or {}),
                ttl_seconds_after_finished=cj.ttl_seconds_after_finished,
            ))
        updated = shallow_copy(cj)
        updated.last_schedule_time = due
        self.store.add_cron_job(updated)
