"""EndpointSliceMirroring controller (reference
``pkg/controller/endpointslicemirroring``): selectorless Services have
their Endpoints managed manually; this loop mirrors those custom
Endpoints objects into EndpointSlices (labelled as mirrored and
owner-bound to the Endpoints object) so slice consumers see a uniform
API. Services WITH selectors are the endpointslice controller's job and
are skipped here (endpointslicemirroring_controller.go shouldMirror).
"""

from __future__ import annotations

from kubernetes_tpu.api.types import (
    EndpointAddress,
    EndpointSlice,
    ObjectMeta,
)
from kubernetes_tpu.controllers.base import Controller, owner_ref, split_key

SERVICE_NAME_LABEL = "kubernetes.io/service-name"
MANAGED_BY_LABEL = "endpointslice.kubernetes.io/managed-by"
MIRRORING_CONTROLLER = "endpointslicemirroring-controller.k8s.io"


class EndpointSliceMirroringController(Controller):
    name = "endpointslicemirroring"
    max_endpoints_per_slice = 100

    def register(self) -> None:
        self.factory.informer_for("Endpoints").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=self.enqueue,
        )
        self.factory.informer_for("Service").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
        )

    def _mirrored_slices(self, namespace: str, service: str):
        return [
            es for es in self.store.list_endpoint_slices()
            if es.namespace == namespace
            and es.metadata.labels.get(SERVICE_NAME_LABEL) == service
            and es.metadata.labels.get(MANAGED_BY_LABEL)
            == MIRRORING_CONTROLLER
        ]

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        ep = self.store.get_object("Endpoints", ns, name)
        svc = self.store.get_object("Service", ns, name)
        existing = self._mirrored_slices(ns, name)
        # mirror only when the Endpoints' Service exists AND is
        # selectorless (shouldMirror)
        if ep is None or svc is None or svc.selector:
            for es in existing:
                self.store.delete_object("EndpointSlice", ns, es.name)
            return
        addresses = [
            EndpointAddress(ip=a.ip, node_name=a.node_name,
                            target_pod=a.target_pod)
            for a in sorted(ep.addresses, key=lambda a: a.ip)
        ]
        chunks = [
            addresses[i:i + self.max_endpoints_per_slice]
            for i in range(0, len(addresses),
                           self.max_endpoints_per_slice)
        ] or [[]]
        wanted = {}
        for idx, chunk in enumerate(chunks):
            slice_name = f"{name}-mirror-{idx}"
            wanted[slice_name] = EndpointSlice(
                metadata=ObjectMeta(
                    name=slice_name, namespace=ns,
                    labels={
                        SERVICE_NAME_LABEL: name,
                        MANAGED_BY_LABEL: MIRRORING_CONTROLLER,
                    },
                    owner_references=[owner_ref("Endpoints", ep)],
                ),
                endpoints=chunk,
                ports=list(svc.ports),
            )

        def fingerprint(es: EndpointSlice):
            return (
                [(a.ip, a.node_name, a.target_pod) for a in es.endpoints],
                [(p.name, p.port, p.target_port) for p in es.ports],
            )

        current = {es.name: es for es in existing}
        for slice_name, es in wanted.items():
            old = current.get(slice_name)
            if old is None or fingerprint(old) != fingerprint(es):
                self.store.add_endpoint_slice(es)
        for slice_name in current:
            if slice_name not in wanted:
                self.store.delete_object("EndpointSlice", ns, slice_name)
