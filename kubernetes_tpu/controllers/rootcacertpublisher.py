"""Root CA certificate publisher.

Behavioral equivalent of the reference's
``pkg/controller/certificates/rootcacertpublisher/publisher.go:56
NewPublisher``: every active namespace carries a ``kube-root-ca.crt``
ConfigMap holding the cluster CA bundle (the trust anchor pods use to
verify the apiserver), recreated when deleted and overwritten when its
data drifts from the configured root.

The published bundle comes from the same stand-in CA the certificates
signing controller uses (``controllers/certificates.py`` ``CA_KEY``),
so a workload that verifies a kubelet serving cert against this bundle
is checking the identical trust root that signed it.
"""

from __future__ import annotations

import hashlib

from kubernetes_tpu.api.types import ConfigMap, ObjectMeta
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.certificates import CA_KEY

ROOT_CA_CONFIGMAP = "kube-root-ca.crt"


def root_ca_bundle() -> str:
    """The cluster's root CA in PEM shape (publisher.go publishes the
    raw rootCA bytes; the stand-in CA's public fingerprint plays that
    role here)."""
    fp = hashlib.sha256(CA_KEY).hexdigest()
    return (
        "-----BEGIN CERTIFICATE-----\n"
        f"cluster-root-ca-fingerprint: {fp}\n"
        "-----END CERTIFICATE-----\n"
    )


class RootCACertPublisher(Controller):
    name = "root-ca-cert-publisher"

    def register(self) -> None:
        self.factory.informer_for("Namespace").add_event_handler(
            on_add=lambda ns: self.enqueue_key(ns.name),
            on_update=lambda old, new: self.enqueue_key(new.name),
        )
        # deletion or drift of the published ConfigMap re-publishes
        # (publisher.go cmAddedOrUpdated / cmDeleted handlers)
        self.factory.informer_for("ConfigMap").add_event_handler(
            on_add=self._cm_changed,
            on_update=lambda old, new: self._cm_changed(new),
            on_delete=self._cm_changed,
        )

    def _cm_changed(self, cm: ConfigMap) -> None:
        if cm.name == ROOT_CA_CONFIGMAP:
            self.enqueue_key(cm.namespace)

    def sync(self, key: str) -> None:
        ns = self.store.get_namespace(key)
        if ns is None or ns.phase == "Terminating":
            return
        bundle = root_ca_bundle()
        cm = self.store.get_object("ConfigMap", key, ROOT_CA_CONFIGMAP)
        if cm is None:
            self.store.create_object("ConfigMap", ConfigMap(
                metadata=ObjectMeta(name=ROOT_CA_CONFIGMAP, namespace=key),
                data={"ca.crt": bundle},
            ))
            return
        if cm.data.get("ca.crt") != bundle:
            def mutate(obj) -> bool:
                obj.data = {"ca.crt": bundle}
                return True

            self.store.mutate_object("ConfigMap", key, ROOT_CA_CONFIGMAP,
                                     mutate)
