"""HorizontalPodAutoscaler controller.

Behavioral equivalent of the reference's ``pkg/controller/podautoscaler``
(horizontal.go reconcileAutoscaler + replica_calculator.go): observe the
target workload's average CPU utilization (usage / request per pod),
compute

    desired = ceil(current_replicas * avg_utilization / target)

apply the 10% tolerance band around 1.0, clamp to [min, max], and patch
the target's ``spec.replicas``.

Pod usage comes from a pluggable metrics provider — upstream reads the
resource-metrics API (metrics-server); this harness's default provider
reads the ``metrics.alpha.kubernetes.io/cpu-usage`` pod annotation
(milliCPU), which the kubelet stats stub (or a test) publishes. The
seam, not the transport, is the parity surface.
"""

from __future__ import annotations

import math
import time
from typing import List, Optional

from kubernetes_tpu.api.types import shallow_copy
from kubernetes_tpu.controllers.base import Controller, is_owned_by, split_key
from kubernetes_tpu.scheduler.types import compute_pod_resource_request

USAGE_ANNOTATION = "metrics.alpha.kubernetes.io/cpu-usage"
TOLERANCE = 0.10  # reference horizontal-pod-autoscaler-tolerance


class AnnotationMetricsProvider:
    """Default provider: per-pod CPU usage (milli) from the pod's
    usage annotation; None when the pod reports no sample."""

    def pod_cpu_usage_milli(self, pod) -> Optional[int]:
        raw = pod.metadata.annotations.get(USAGE_ANNOTATION)
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError:
            return None


class HorizontalPodAutoscalerController(Controller):
    name = "horizontalpodautoscaler"

    RESYNC_SECONDS = 1.0  # reference --horizontal-pod-autoscaler-sync-period
    #                       is 15s; scaled for the harness
    # reference --horizontal-pod-autoscaler-downscale-stabilization (5m
    # upstream; scaled for the harness, injectable in tests): a
    # downscale only applies the HIGHEST recommendation of the window,
    # so a brief utilization dip can't flap replicas away
    # (horizontal.go stabilizeRecommendation)
    DOWNSCALE_STABILIZATION_SECONDS = 5.0

    metrics_provider = AnnotationMetricsProvider()

    def register(self) -> None:
        self.factory.informer_for("HorizontalPodAutoscaler").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
        )
        self.pod_lister = self.factory.lister_for("Pod")
        # per-HPA recommendation history for the stabilization window
        self._recommendations: dict = {}

    def resync(self) -> None:
        live = set()
        for hpa in self.store.list_hpas():
            live.add(f"{hpa.namespace}/{hpa.name}")
            self.enqueue(hpa)
        # drop history of deleted HPAs (the controller runs forever;
        # HPA churn must not accumulate dead keys)
        for key in list(self._recommendations):
            if key not in live:
                del self._recommendations[key]

    # ------------------------------------------------------------------
    SCALABLE_KINDS = ("Deployment", "ReplicaSet", "ReplicationController")

    def _target(self, hpa):
        kind = hpa.scale_target_ref.get("kind")
        name = hpa.scale_target_ref.get("name")
        if kind not in self.SCALABLE_KINDS or not name:
            return kind, None
        return kind, self.store.get_object(kind, hpa.namespace, name)

    def _target_pods(self, hpa, kind, target) -> List:
        if kind == "Deployment":
            # deployment pods are owned via ReplicaSets: match by the
            # deployment's selector instead of walking the RS chain
            if target.selector is None:
                return []
            sel = target.selector.to_selector()
            return [
                p for p in self.pod_lister.by_namespace(hpa.namespace)
                if sel.matches(p.metadata.labels)
            ]
        return [
            p for p in self.pod_lister.by_namespace(hpa.namespace)
            if is_owned_by(p, kind, target)
        ]

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        hpa = self.store.get_hpa(ns, name)
        if hpa is None:
            return
        kind, target = self._target(hpa)
        if target is None:
            return
        current = target.replicas
        pods = [
            p for p in self._target_pods(hpa, kind, target)
            if p.status.phase not in ("Succeeded", "Failed")
            and p.metadata.deletion_timestamp is None
        ]
        ratios = []
        missing = 0
        for p in pods:
            request = compute_pod_resource_request(p).milli_cpu
            if request <= 0:
                continue
            usage = self.metrics_provider.pod_cpu_usage_milli(p)
            if usage is None:
                missing += 1
                continue
            ratios.append(usage / request)
        if not ratios or current <= 0:
            self._publish(hpa, current, current, None)
            return
        target_frac = hpa.target_cpu_utilization_percentage / 100.0
        avg = sum(ratios) / len(ratios)
        utilization = avg * 100.0
        scale_ratio = avg / target_frac
        if missing:
            # replica_calculator.go missing-metrics rebalance: pods
            # without samples (e.g. freshly scaled-up replicas) assume
            # 0% on scale-up and 100%-of-request on scale-down, so a
            # half-reported fleet can't runaway-scale in either
            # direction; a rebalance that crosses 1.0 means no scale
            if scale_ratio > 1.0:
                rebalanced = sum(ratios) / (len(ratios) + missing)
            else:
                rebalanced = (sum(ratios) + missing) / (
                    len(ratios) + missing
                )
            new_ratio = rebalanced / target_frac
            if (new_ratio > 1.0) != (scale_ratio > 1.0):
                scale_ratio = 1.0
            else:
                scale_ratio = new_ratio
        if abs(scale_ratio - 1.0) <= TOLERANCE:
            desired = current  # within tolerance: no scale
        else:
            # base on the OBSERVED pod count (replica_calculator.go uses
            # readyPodCount, not spec.replicas): after a scale-up the
            # spec leads the actual pods, and multiplying the spec by a
            # still-hot average would compound the scale every tick
            desired = math.ceil((len(ratios) + missing) * scale_ratio)
        desired = max(hpa.min_replicas, min(hpa.max_replicas, desired))
        desired = self._stabilize(f"{ns}/{name}", current, desired)
        if desired != current:
            updated = shallow_copy(target)
            updated.metadata = shallow_copy(target.metadata)
            updated.replicas = desired
            self.store.update_object(kind, updated)
        self._publish(hpa, current, desired, int(round(utilization)),
                      scaled=desired != current)

    def _stabilize(self, key: str, current: int, desired: int) -> int:
        """horizontal.go stabilizeRecommendation: record every
        recommendation; a DOWNSCALE is clamped to the maximum
        recommendation still inside the stabilization window (upscales
        apply immediately)."""
        now = time.time()
        window = self.DOWNSCALE_STABILIZATION_SECONDS
        hist = self._recommendations.setdefault(key, [])
        hist.append((now, desired))
        del hist[: max(0, len(hist) - 64)]  # bounded memory
        if desired >= current:
            return desired
        floor = max(
            (d for t, d in hist if now - t <= window), default=desired
        )
        return min(current, max(desired, floor))

    def _publish(self, hpa, current: int, desired: int,
                 utilization: Optional[int], scaled: bool = False) -> None:
        if (hpa.current_replicas == current
                and hpa.desired_replicas == desired
                and hpa.current_cpu_utilization_percentage == utilization):
            return
        updated = shallow_copy(hpa)
        updated.metadata = shallow_copy(hpa.metadata)
        updated.current_replicas = current
        updated.desired_replicas = desired
        updated.current_cpu_utilization_percentage = utilization
        if scaled:
            updated.last_scale_time = time.time()
        self.store.update_object("HorizontalPodAutoscaler", updated)
