"""TTL controller (node annotations).

Behavioral equivalent of the reference's ``pkg/controller/ttl``
(ttl_controller.go): annotates every node with
``node.alpha.kubernetes.io/ttl`` — the secret/configmap cache TTL the
kubelet should use — scaled by cluster size (bigger clusters get longer
TTLs to shed apiserver load). The reference's ladder
(``ttlBoundaries``): 0s up to 100 nodes, 15s up to 500, 30s up to 1000,
60s up to 2000, 300s beyond.
"""

from __future__ import annotations

from kubernetes_tpu.api.types import Node
from kubernetes_tpu.controllers.base import Controller

TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"

# (max cluster size, ttl seconds) — ttl_controller.go ttlBoundaries
_BOUNDARIES = ((100, 0), (500, 15), (1000, 30), (2000, 60))
_MAX_TTL = 300


def ttl_for_cluster_size(n: int) -> int:
    for bound, ttl in _BOUNDARIES:
        if n <= bound:
            return ttl
    return _MAX_TTL


class TTLController(Controller):
    name = "ttl"

    def register(self) -> None:
        self._last_ttl = None
        self.factory.informer_for("Node").add_event_handler(
            on_add=lambda n: self._maybe_resync(new_node=n.name),
            on_delete=lambda n: self._maybe_resync(),
        )

    def _maybe_resync(self, new_node: str = "") -> None:
        """Re-enqueue the WHOLE cluster only when the size crossed a TTL
        tier boundary (the reference only resyncs on boundary crossings
        — enqueueing n nodes on each of n adds is quadratic at
        bootstrap). Otherwise only the new node needs its annotation."""
        ttl = ttl_for_cluster_size(len(self.store.list_nodes()))
        if ttl != self._last_ttl:
            self._last_ttl = ttl
            for n in self.store.list_nodes():
                self.enqueue_key(n.name)
        elif new_node:
            self.enqueue_key(new_node)

    def sync(self, key: str) -> None:
        want = str(ttl_for_cluster_size(len(self.store.list_nodes())))

        def mutate(n: Node) -> bool:
            if n.metadata.annotations.get(TTL_ANNOTATION) == want:
                return False
            n.metadata.annotations = dict(n.metadata.annotations)
            n.metadata.annotations[TTL_ANNOTATION] = want
            return True

        self.store.mutate_object("Node", "", key, mutate)
