"""PVC/PV protection controllers.

Behavioral equivalents of the reference's
``pkg/controller/volume/pvcprotection`` and ``.../pvprotection``: every
PVC (PV) gets the ``kubernetes.io/pvc-protection``
(``kubernetes.io/pv-protection``) finalizer on arrival, so a delete
request only MARKS the object while it is in use; the controller
removes the finalizer — letting the physical delete proceed — once no
pod references the PVC (no bound PVC references the PV).
"""

from __future__ import annotations

from kubernetes_tpu.controllers.base import Controller, split_key

PVC_FINALIZER = "kubernetes.io/pvc-protection"
PV_FINALIZER = "kubernetes.io/pv-protection"


class PVCProtectionController(Controller):
    name = "pvc-protection"

    def register(self) -> None:
        self.factory.informer_for("PersistentVolumeClaim").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
        )
        # pod deletion may release the last user of a deleting PVC
        self.factory.informer_for("Pod").add_event_handler(
            on_delete=self._pod_gone,
            on_update=lambda old, new: self._pod_gone(old),
        )
        self.pod_lister = self.factory.lister_for("Pod")

    def _pod_gone(self, pod) -> None:
        for vol in pod.spec.volumes:
            if vol.persistent_volume_claim:
                self.enqueue_key(
                    f"{pod.namespace}/{vol.persistent_volume_claim}"
                )

    def _in_use(self, namespace: str, claim: str) -> bool:
        for p in self.pod_lister.by_namespace(namespace):
            # a deletion-MARKED pod may still be running through its
            # finalizers/grace period and still mounts the claim
            # (upstream podIsShutDown: only actually-terminated pods
            # release protection); any pod that still EXISTS and is not
            # terminal counts as a user
            if p.status.phase in ("Succeeded", "Failed"):
                continue
            for vol in p.spec.volumes:
                if vol.persistent_volume_claim == claim:
                    return True
        return False

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pvc = self.store.get_pvc(ns, name)
        if pvc is None:
            return
        if pvc.metadata.deletion_timestamp is None:
            # live claim: ensure the finalizer is on
            self.store.add_finalizer(
                "PersistentVolumeClaim", ns, name, PVC_FINALIZER
            )
            return
        if not self._in_use(ns, name):
            self.store.remove_finalizer(
                "PersistentVolumeClaim", ns, name, PVC_FINALIZER
            )


class PVProtectionController(Controller):
    name = "pv-protection"

    def register(self) -> None:
        self.factory.informer_for("PersistentVolume").add_event_handler(
            on_add=lambda pv: self.enqueue_key(pv.name),
            on_update=lambda old, new: self.enqueue_key(new.name),
        )
        self.factory.informer_for("PersistentVolumeClaim").add_event_handler(
            on_delete=lambda pvc: self._pvc_gone(pvc),
            on_update=lambda old, new: self._pvc_gone(old),
        )

    def _pvc_gone(self, pvc) -> None:
        if pvc.volume_name:
            self.enqueue_key(pvc.volume_name)

    def _bound(self, name: str) -> bool:
        for pvc in self.store.list_all_pvcs():
            if pvc.volume_name == name and \
                    pvc.metadata.deletion_timestamp is None:
                return True
        return False

    def sync(self, key: str) -> None:
        pv = self.store.get_pv(key)
        if pv is None:
            return
        if pv.metadata.deletion_timestamp is None:
            self.store.add_finalizer(
                "PersistentVolume", "", key, PV_FINALIZER
            )
            return
        if not self._bound(key):
            self.store.remove_finalizer(
                "PersistentVolume", "", key, PV_FINALIZER
            )
