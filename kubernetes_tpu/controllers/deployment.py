"""Deployment reconcile loop (manages ReplicaSets).

Behavioral equivalent of the reference's
``pkg/controller/deployment/deployment_controller.go`` + ``sync.go``:
a Deployment owns one ReplicaSet per pod-template revision (identified by
a template hash, reference ``pod_template_hash``); sync scales the
current-revision RS up to ``spec.replicas`` and old-revision RSes to 0
(the Recreate/rolling surface collapsed to its fixed point — the
scheduler-facing behavior the harness needs).
"""

from __future__ import annotations

import hashlib
import json

import copy

from kubernetes_tpu.api.labels import LabelSelector
from kubernetes_tpu.api.types import Deployment, ReplicaSet, WorkloadStatus
from kubernetes_tpu.controllers.base import (
    Controller,
    controller_of,
    owner_ref,
    split_key,
    with_status,
)


def template_hash(template: dict) -> str:
    return hashlib.sha1(
        json.dumps(template or {}, sort_keys=True).encode()
    ).hexdigest()[:10]


# reference pkg/controller/deployment/util/deployment_util.go:38-44
REVISION_ANNOTATION = "deployment.kubernetes.io/revision"
CHANGE_CAUSE_ANNOTATION = "kubernetes.io/change-cause"


def rs_revision(rs: ReplicaSet) -> int:
    try:
        return int(rs.metadata.annotations.get(REVISION_ANNOTATION, "0"))
    except ValueError:
        return 0


class DeploymentController(Controller):
    name = "deployment"

    def register(self) -> None:
        self.factory.informer_for("Deployment").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=self.enqueue,
        )
        self.factory.informer_for("ReplicaSet").add_event_handler(
            on_add=self._rs_changed,
            on_update=lambda old, new: self._rs_changed(new),
            on_delete=self._rs_changed,
        )
        self.rs_lister = self.factory.lister_for("ReplicaSet")

    def _rs_changed(self, rs: ReplicaSet) -> None:
        ref = controller_of(rs)
        if ref is not None and ref.get("kind") == "Deployment":
            self.enqueue_key(f"{rs.metadata.namespace}/{ref['name']}")

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        deploy = self.store.get_deployment(ns, name)
        if deploy is None:
            return
        want_hash = template_hash(deploy.template)
        owned = [
            rs for rs in self.rs_lister.by_namespace(ns)
            if any(r.get("controller") and r.get("kind") == "Deployment"
                   and r.get("uid") == deploy.metadata.uid
                   for r in rs.metadata.owner_references)
        ]
        current = None
        for rs in owned:
            if rs.metadata.labels.get("pod-template-hash") == want_hash:
                current = rs
                break
        max_rev = max((rs_revision(rs) for rs in owned), default=0)
        if current is None:
            current = self._new_rs(deploy, want_hash, max_rev + 1)
            owned.append(current)
        else:
            # an old template re-becoming current (rollback) takes a
            # FRESH max+1 revision, like the reference's
            # SetNewReplicaSetAnnotations — history is a sequence of
            # deploys, not a set of templates
            if rs_revision(current) != max_rev:
                bumped = copy.copy(current)
                bumped.metadata = copy.copy(current.metadata)
                bumped.metadata.annotations = dict(
                    current.metadata.annotations)
                bumped.metadata.annotations[REVISION_ANNOTATION] = str(
                    max_rev + 1)
                cause = deploy.metadata.annotations.get(
                    CHANGE_CAUSE_ANNOTATION)
                if cause:
                    bumped.metadata.annotations[
                        CHANGE_CAUSE_ANNOTATION] = cause
                self.store.update_replica_set(bumped)
                current = bumped
            if current.replicas != deploy.replicas:
                current = self._scale_rs(current, deploy.replicas)
        owned = [
            self._scale_rs(rs, 0)
            if rs.metadata.uid != current.metadata.uid and rs.replicas != 0
            else rs
            for rs in owned
        ]
        status = WorkloadStatus(
            replicas=sum(rs.status.replicas for rs in owned),
            ready_replicas=sum(rs.status.ready_replicas for rs in owned),
        )
        if status != deploy.status:
            self.store.update_deployment(with_status(deploy, status))

    def _scale_rs(self, rs: ReplicaSet, replicas: int) -> ReplicaSet:
        scaled = copy.copy(rs)
        scaled.metadata = copy.copy(rs.metadata)
        scaled.replicas = replicas
        self.store.update_replica_set(scaled)
        return scaled

    def _new_rs(self, deploy: Deployment, want_hash: str,
                revision: int = 1) -> ReplicaSet:
        template = json.loads(json.dumps(deploy.template or {}))
        labels = dict(template.get("metadata", {}).get("labels") or {})
        labels["pod-template-hash"] = want_hash
        template.setdefault("metadata", {})["labels"] = labels
        sel = deploy.selector or LabelSelector()
        match = dict(sel.match_labels)
        match["pod-template-hash"] = want_hash
        rs = ReplicaSet(
            selector=LabelSelector(match_labels=match,
                                   match_expressions=list(sel.match_expressions)),
            replicas=deploy.replicas,
            template=template,
        )
        rs.metadata.name = f"{deploy.metadata.name}-{want_hash}"
        rs.metadata.namespace = deploy.metadata.namespace
        rs.metadata.labels = labels
        rs.metadata.owner_references = [owner_ref("Deployment", deploy)]
        rs.metadata.annotations[REVISION_ANNOTATION] = str(revision)
        cause = deploy.metadata.annotations.get(CHANGE_CAUSE_ANNOTATION)
        if cause:
            rs.metadata.annotations[CHANGE_CAUSE_ANNOTATION] = cause
        self.store.add_replica_set(rs)
        return rs
