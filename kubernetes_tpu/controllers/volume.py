"""PersistentVolume binder controller.

Behavioral equivalent of the reference's PV controller
(``pkg/controller/volume/persistentvolume/pv_controller.go``) in the shape
scheduler_perf uses it (``test/integration/scheduler_perf/util.go:109``
StartFakePVController): Immediate-mode PVCs are matched to Available PVs
by storage class, access modes and capacity; WaitForFirstConsumer PVCs are
left for the scheduler's VolumeBinding plugin to assume/commit.
"""

from __future__ import annotations

from kubernetes_tpu.api.types import PersistentVolume, PersistentVolumeClaim
from kubernetes_tpu.controllers.base import Controller, split_key


class PersistentVolumeController(Controller):
    name = "persistentvolume-binder"

    def register(self) -> None:
        self.factory.informer_for("PersistentVolumeClaim").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
        )
        self.factory.informer_for("PersistentVolume").add_event_handler(
            on_add=lambda pv: self._all_pending_pvcs(),
        )
        self.pvc_lister = self.factory.lister_for("PersistentVolumeClaim")

    def _all_pending_pvcs(self) -> None:
        for pvc in self.store.list_all_pvcs():
            if pvc.phase == "Pending":
                self.enqueue(pvc)

    def _binding_mode(self, pvc: PersistentVolumeClaim) -> str:
        if not pvc.storage_class_name:
            return "Immediate"
        sc = self.store.get_storage_class(pvc.storage_class_name)
        return sc.volume_binding_mode if sc else "Immediate"

    @staticmethod
    def _matches(pv: PersistentVolume, pvc: PersistentVolumeClaim) -> bool:
        if pv.phase != "Available" or pv.claim_ref:
            return False
        if pv.storage_class_name != (pvc.storage_class_name or ""):
            return False
        if pvc.access_modes and not set(pvc.access_modes) <= set(pv.access_modes):
            return False
        want = pvc.requests.get("storage")
        have = pv.capacity.get("storage")
        if want is not None and (have is None or have.nano < want.nano):
            return False
        return True

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pvc = self.store.get_pvc(ns, name)
        if pvc is None or pvc.phase != "Pending":
            return
        if self._binding_mode(pvc) != "Immediate":
            return  # WaitForFirstConsumer: scheduler VolumeBinding binds
        for pv in self.store.list_pvs():
            if self._matches(pv, pvc):
                self.store.bind_pv(pv.name, ns, name)
                return
