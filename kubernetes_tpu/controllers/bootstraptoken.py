"""Bootstrap-token controllers (reference
``pkg/controller/bootstrap/{bootstrapsigner,tokencleaner}.go``, wired in
``cmd/kube-controller-manager/app/bootstrap.go``):

- **bootstrapsigner**: maintains JWS-style signatures over the
  ``cluster-info`` ConfigMap in kube-public, one per bootstrap-token
  Secret (``jws-kubeconfig-<tokenid>``), so joining nodes can verify
  cluster-info with only their token. The signature is an HMAC stand-in
  with the same binding (token id+secret over the kubeconfig payload).
- **tokencleaner**: deletes bootstrap-token Secrets past their
  ``expiration``.
"""

from __future__ import annotations

import hashlib
import hmac
import time

from kubernetes_tpu.api.types import ConfigMap
from kubernetes_tpu.controllers.base import Controller

BOOTSTRAP_TOKEN_SECRET_TYPE = "bootstrap.kubernetes.io/token"
KUBE_PUBLIC = "kube-public"
CLUSTER_INFO = "cluster-info"
KUBECONFIG_KEY = "kubeconfig"
JWS_PREFIX = "jws-kubeconfig-"


def sign_payload(payload: str, token_id: str, token_secret: str) -> str:
    return hmac.new(
        f"{token_id}.{token_secret}".encode(), payload.encode(),
        hashlib.sha256,
    ).hexdigest()


def _bootstrap_tokens(store):
    """token-id -> secret object, for usable signing tokens."""
    out = {}
    for s in store.list_objects("Secret"):
        if s.type != BOOTSTRAP_TOKEN_SECRET_TYPE:
            continue
        token_id = s.data.get("token-id")
        if token_id and s.data.get("token-secret") and \
                s.data.get("usage-bootstrap-signing") == "true":
            out[token_id] = s
    return out


class BootstrapSignerController(Controller):
    name = "bootstrapsigner"
    RESYNC_SECONDS = 5.0

    def register(self) -> None:
        self.factory.informer_for("Secret").add_event_handler(
            on_add=lambda s: self.enqueue_key("sign"),
            on_update=lambda o, n: self.enqueue_key("sign"),
            on_delete=lambda s: self.enqueue_key("sign"),
        )
        self.factory.informer_for("ConfigMap").add_event_handler(
            on_add=lambda c: self.enqueue_key("sign"),
            on_update=lambda o, n: self.enqueue_key("sign"),
        )

    def resync(self) -> None:
        self.enqueue_key("sign")

    def sync(self, key: str) -> None:
        cm = self.store.get_object("ConfigMap", KUBE_PUBLIC, CLUSTER_INFO)
        if cm is None:
            return
        payload = cm.data.get(KUBECONFIG_KEY, "")
        tokens = {
            tid: s for tid, s in _bootstrap_tokens(self.store).items()
        }
        want = {
            JWS_PREFIX + tid: sign_payload(
                payload, tid, s.data["token-secret"]
            )
            for tid, s in tokens.items()
        }
        have = {k: v for k, v in cm.data.items()
                if k.startswith(JWS_PREFIX)}
        if have == want:
            return

        def mutate(c: ConfigMap) -> bool:
            data = {k: v for k, v in c.data.items()
                    if not k.startswith(JWS_PREFIX)}
            data.update(want)
            if data == c.data:
                return False
            c.data = data
            return True

        self.store.mutate_object("ConfigMap", KUBE_PUBLIC, CLUSTER_INFO,
                                 mutate)


class TokenCleanerController(Controller):
    name = "tokencleaner"
    RESYNC_SECONDS = 5.0

    def register(self) -> None:
        pass

    def resync(self) -> None:
        self.enqueue_key("sweep")

    def sync(self, key: str) -> None:
        now = time.time()
        for s in self.store.list_objects("Secret"):
            if s.type != BOOTSTRAP_TOKEN_SECRET_TYPE:
                continue
            exp = s.data.get("expiration")
            if not exp:
                continue
            try:
                exp_t = float(exp)
            except ValueError:
                continue
            if exp_t <= now:
                self.store.delete_object("Secret", s.namespace, s.name)
