"""Job reconcile loop.

Behavioral equivalent of the reference's ``pkg/controller/job/job_controller.go``
syncJob: keep up to ``parallelism`` active pods until ``completions`` pods
have Succeeded; count terminal pods into status. Pods reach Succeeded via
the (hollow) kubelet marking container completion — without kubelets the
job stays active, matching the reference's behavior with no nodes.
"""

from __future__ import annotations

from kubernetes_tpu.api.types import FAILED, SUCCEEDED, Job, Pod, WorkloadStatus
from kubernetes_tpu.controllers.base import (
    Controller,
    is_owned_by,
    owner_ref,
    split_key,
    with_status,
)


class JobController(Controller):
    name = "job"

    def register(self) -> None:
        self.factory.informer_for("Job").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=self.enqueue,
        )
        self.factory.informer_for("Pod").add_event_handler(
            on_add=self._pod_changed,
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_changed,
        )
        self.pod_lister = self.factory.lister_for("Pod")

    def _pod_changed(self, pod: Pod) -> None:
        for r in pod.metadata.owner_references:
            if r.get("controller") and r.get("kind") == "Job":
                self.enqueue_key(f"{pod.namespace}/{r['name']}")

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        job = self.store.get_job(ns, name)
        if job is None:
            return
        owned = [
            p for p in self.pod_lister.by_namespace(ns)
            if is_owned_by(p, "Job", job)
        ]
        succeeded = sum(1 for p in owned if p.status.phase == SUCCEEDED)
        failed = sum(1 for p in owned if p.status.phase == FAILED)
        active = [
            p for p in owned
            if p.status.phase not in (SUCCEEDED, FAILED)
            and p.metadata.deletion_timestamp is None
        ]
        remaining = job.completions - succeeded
        want_active = max(0, min(job.parallelism, remaining))
        for _ in range(want_active - len(active)):
            self._create_pod(job)
        for p in active[want_active:] if want_active < len(active) else []:
            self.store.delete_pod(p.namespace, p.name)
        status = WorkloadStatus(
            replicas=min(len(active), want_active),
            succeeded=succeeded,
            failed=failed,
        )
        # completion anchors the ttl-after-finished countdown; sticky
        # once set (the reference stamps CompletionTime exactly once) —
        # even if the finished condition stops holding later (e.g. the
        # counted terminal pods get deleted), the anchor must survive
        status.completion_time = job.status.completion_time
        if status.completion_time is None and (
            succeeded >= job.completions or failed > 0
        ):
            import time as _time

            status.completion_time = _time.time()
        if status != job.status:
            self.store.add_job(with_status(job, status))

    def _create_pod(self, job: Job) -> None:
        pod = Pod.from_dict(dict(job.template or {}))
        pod.metadata.namespace = job.metadata.namespace
        pod.metadata.name = f"{job.metadata.name}-{pod.metadata.uid}"
        pod.metadata.owner_references = list(pod.metadata.owner_references) + [
            owner_ref("Job", job)
        ]
        # jobs run to completion: the hollow kubelet uses this annotation
        # to transition Running -> Succeeded
        pod.metadata.annotations.setdefault("kubernetes-tpu/run-to-completion", "true")
        self.store.create_pod(pod)
