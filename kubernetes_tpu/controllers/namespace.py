"""Namespace lifecycle controller.

Behavioral equivalent of the reference's ``pkg/controller/namespace``
(namespaced_resources_deleter.go): when a Namespace enters the
Terminating phase (deletion requested), delete every namespaced object
it contains, then finalize — remove the Namespace itself. Content
deletion is idempotent and re-queued until the namespace is empty,
mirroring ``Delete``'s retry-until-clean loop.
"""

from __future__ import annotations

from kubernetes_tpu.api.types import Namespace
from kubernetes_tpu.controllers.base import Controller


class NamespaceController(Controller):
    name = "namespace"

    # kinds the deleter sweeps (every namespaced kind the store knows,
    # discovered dynamically — the reference enumerates via discovery)
    def register(self) -> None:
        # cluster-scoped: key by bare name (ObjectMeta defaults the
        # namespace field, so the generic ns/name enqueue is wrong here)
        self.factory.informer_for("Namespace").add_event_handler(
            on_add=lambda ns: self.enqueue_key(ns.name),
            on_update=lambda old, new: self.enqueue_key(new.name),
        )

    def sync(self, key: str) -> None:
        ns = self.store.get_namespace(key)
        if ns is None:
            return
        if ns.phase != "Terminating" and \
                ns.metadata.deletion_timestamp is None:
            return
        # mark Terminating first (kubectl delete ns sets phase before
        # content deletion; the REST path's NamespaceLifecycle admission
        # rejects new creates into Terminating namespaces from here on —
        # direct store writers bypass admission, so the sweep re-queues
        # until the namespace is actually empty)
        if ns.phase != "Terminating":
            updated = Namespace(metadata=ns.metadata, phase="Terminating")
            self.store.update_object("Namespace", updated)
        remaining = 0
        for kind in self.store.known_kinds():
            if kind == "Namespace" or not self.store.kind_is_namespaced(kind):
                continue
            for obj in self.store.list_objects(kind, namespace=key):
                self.store.delete_object(
                    kind, obj.metadata.namespace, obj.metadata.name
                )
                remaining += 1
        if remaining:
            # deletes may cascade more objects (owner refs): re-check
            self.queue.add_rate_limited(key)
            return
        self.store.delete_namespace(key)
