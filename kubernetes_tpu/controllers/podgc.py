"""Pod garbage collector.

Behavioral equivalent of the reference's ``pkg/controller/podgc``
(gc_controller.go): periodically

- deletes terminated (Succeeded/Failed) pods beyond the configured
  threshold, oldest first (``gcTerminated``; reference default
  ``--terminated-pod-gc-threshold=12500``),
- deletes ORPHANED pods — bound to a node that no longer exists
  (``gcOrphaned``),
- deletes unscheduled pods that are terminating
  (``gcUnscheduledTerminating``).
"""

from __future__ import annotations

from kubernetes_tpu.api.types import FAILED, SUCCEEDED
from kubernetes_tpu.controllers.base import Controller

_SYNC_KEY = "podgc"


class PodGCController(Controller):
    name = "podgc"

    terminated_threshold = 12500
    RESYNC_SECONDS = 20.0  # reference gcCheckPeriod (tests lower this
    #                        per instance, like terminated_threshold)

    def register(self) -> None:
        # event-driven enqueues (node deletes orphan pods immediately;
        # terminal-phase pods feed the threshold sweep) plus the base
        # class's periodic resync as the backstop
        self.factory.informer_for("Node").add_event_handler(
            on_delete=lambda n: self.enqueue_key(_SYNC_KEY),
        )

        def pod_changed(pod) -> None:
            if pod.status.phase in ("Succeeded", "Failed") or \
                    pod.metadata.deletion_timestamp is not None:
                self.enqueue_key(_SYNC_KEY)

        self.factory.informer_for("Pod").add_event_handler(
            on_add=pod_changed,
            on_update=lambda old, new: pod_changed(new),
        )

    def resync(self) -> None:
        self.enqueue_key(_SYNC_KEY)

    def sync(self, key: str) -> None:
        pods = self.store.list_pods()
        nodes = {n.name for n in self.store.list_nodes()}

        # gcTerminated: oldest terminated pods beyond the threshold
        terminated = [
            p for p in pods if p.status.phase in (SUCCEEDED, FAILED)
        ]
        excess = len(terminated) - self.terminated_threshold
        if excess > 0:
            terminated.sort(key=lambda p: p.metadata.creation_timestamp or 0)
            for p in terminated[:excess]:
                self.store.delete_pod(p.namespace, p.name)

        orphaned = 0
        for p in pods:
            # gcOrphaned: bound to a node that no longer exists
            if p.spec.node_name and p.spec.node_name not in nodes:
                self.store.delete_pod(p.namespace, p.name)
                orphaned += 1
            # gcUnscheduledTerminating
            elif not p.spec.node_name and \
                    p.metadata.deletion_timestamp is not None:
                self.store.delete_pod(p.namespace, p.name)
        if orphaned:
            from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

            fabric_metrics().node_evictions_total.inc(
                "orphaned", amount=orphaned)
