"""ServiceAccount controller.

Behavioral equivalent of the reference's ``pkg/controller/serviceaccount``
(serviceaccounts_controller.go): ensure every active namespace carries
the "default" ServiceAccount; recreate it when deleted.
"""

from __future__ import annotations

from kubernetes_tpu.api.types import Namespace, ObjectMeta, ServiceAccount
from kubernetes_tpu.controllers.base import Controller, split_key


class ServiceAccountController(Controller):
    name = "serviceaccount"

    ACCOUNT = "default"

    def register(self) -> None:
        # keys are bare namespace names (Namespace is cluster-scoped)
        self.factory.informer_for("Namespace").add_event_handler(
            on_add=lambda ns: self.enqueue_key(ns.name),
            on_update=lambda old, new: self.enqueue_key(new.name),
        )
        self.factory.informer_for("ServiceAccount").add_event_handler(
            on_delete=lambda sa: self.enqueue_key(sa.namespace),
        )

    def sync(self, key: str) -> None:
        ns = key
        namespace = self.store.get_namespace(ns)
        if namespace is None or namespace.phase == "Terminating":
            return
        if self.store.get_service_account(ns, self.ACCOUNT) is None:
            self.store.add_service_account(ServiceAccount(
                metadata=ObjectMeta(name=self.ACCOUNT, namespace=ns),
            ))
