"""Node lifecycle controller: health monitoring + eviction.

Behavioral equivalent of the reference's
``pkg/controller/nodelifecycle/node_lifecycle_controller.go``
(monitorNodeHealth :337-352): nodes must heartbeat (renew the
``node-<name>`` lease / update Ready condition); a node silent past the
grace period is marked NotReady, tainted ``node.kubernetes.io/unreachable``
(NoExecute), and after the eviction grace its pods are deleted so their
controllers replace them elsewhere.
"""

from __future__ import annotations

import threading
from typing import Dict

from kubernetes_tpu.api.types import (
    TAINT_NODE_UNREACHABLE,
    Node,
    PodCondition,
    Taint,
)
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.utils.clock import RealClock

UNREACHABLE_TAINT = TAINT_NODE_UNREACHABLE


class NodeLifecycleController(Controller):
    name = "nodelifecycle"
    monitor_interval = 1.0
    grace_period = 40.0        # reference nodeMonitorGracePeriod default
    eviction_grace = 10.0      # collapsed pod-eviction-timeout

    def __init__(self, store, factory, clock=None):
        self._clock = clock or RealClock()
        self._not_ready_since: Dict[str, float] = {}
        self._first_seen: Dict[str, float] = {}
        super().__init__(store, factory)

    def register(self) -> None:
        self.node_lister = self.factory.lister_for("Node")
        self.pod_lister = self.factory.lister_for("Pod")
        # purge health bookkeeping on delete, or a re-registered node with
        # the same name inherits stale not-ready timestamps and gets its
        # pods evicted on the first monitor tick instead of a grace period
        self.factory.informer_for("Node").add_event_handler(
            on_delete=lambda n: (
                self._not_ready_since.pop(n.name, None),
                self._first_seen.pop(n.name, None),
            ),
        )
        self._monitor_stop = threading.Event()

    def run(self) -> None:
        super().run()
        t = threading.Thread(target=self._monitor_loop, daemon=True,
                             name="node-health-monitor")
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._monitor_stop.set()
        super().stop()

    def _monitor_loop(self) -> None:
        while not self._monitor_stop.wait(self.monitor_interval):
            self.monitor_node_health()

    # ------------------------------------------------------------------
    def heartbeat(self, node_name: str) -> None:
        """Called by the (hollow) kubelet: renew the node lease."""
        self.store.try_acquire_or_renew(
            f"node-{node_name}", node_name, self._clock.now(),
            self.grace_period,
        )

    def monitor_node_health(self) -> None:
        now = self._clock.now()
        for node in self.node_lister.list():
            # a node that has never heartbeated gets the full grace period
            # from first observation (reference grants
            # nodeMonitorGracePeriod from node creation). The lease
            # OUTLIVES node deletion, so "never heartbeated" must mean
            # "not since THIS incarnation registered" — a node deleted
            # and recreated under the same name (flap re-registration)
            # would otherwise inherit the old incarnation's stale renew
            # time and be tainted/evicted on the first monitor tick.
            first_seen = self._first_seen.setdefault(node.name, now)
            fresh = self._lease_fresh(node.name, now)
            if not fresh:
                info = self.store.lease_info(f"node-{node.name}")
                if info is None or info[1] <= first_seen:
                    fresh = now - first_seen <= self.grace_period
            if fresh:
                if node.name in self._not_ready_since:
                    del self._not_ready_since[node.name]
                    self._mark_ready(node)
            else:
                since = self._not_ready_since.setdefault(node.name, now)
                self._mark_not_ready(node)
                if now - since >= self.eviction_grace:
                    self._evict_pods(node)

    def _lease_fresh(self, node_name: str, now: float) -> bool:
        info = self.store.lease_info(f"node-{node_name}")
        return info is not None and now - info[1] <= self.grace_period

    def _mark_not_ready(self, node: Node) -> None:
        if any(t.key == UNREACHABLE_TAINT for t in node.spec.taints):
            return
        node = self._copy(node)
        node.spec.taints = list(node.spec.taints) + [
            Taint(key=UNREACHABLE_TAINT, effect="NoExecute")
        ]
        node.status.conditions = [
            c for c in node.status.conditions if c.type != "Ready"
        ] + [PodCondition("Ready", "False", "NodeStatusUnknown",
                          "node heartbeat lost")]
        self.store.update_node(node)

    def _mark_ready(self, node: Node) -> None:
        node = self._copy(node)
        node.spec.taints = [
            t for t in node.spec.taints if t.key != UNREACHABLE_TAINT
        ]
        node.status.conditions = [
            c for c in node.status.conditions if c.type != "Ready"
        ] + [PodCondition("Ready", "True", "KubeletReady", "")]
        self.store.update_node(node)

    @staticmethod
    def _copy(node: Node) -> Node:
        """Never mutate informer-cached instances in place."""
        import copy

        new = copy.copy(node)
        new.metadata = copy.copy(node.metadata)
        new.spec = copy.copy(node.spec)
        new.status = copy.copy(node.status)
        return new

    def _evict_pods(self, node: Node) -> None:
        from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

        evicted = 0
        for pod in self.pod_lister.list():
            if pod.spec.node_name != node.name:
                continue
            if any(t.key == UNREACHABLE_TAINT
                   and t.toleration_seconds is None
                   for t in pod.spec.tolerations):
                continue  # tolerates unreachable forever (e.g. daemons)
            self.store.delete_pod(pod.namespace, pod.name)
            evicted += 1
        if evicted:
            fabric_metrics().node_evictions_total.inc(
                "unreachable", amount=evicted)

    def sync(self, key: str) -> None:  # queue unused; monitor loop drives
        pass
