"""EndpointSlice controller.

Behavioral equivalent of the reference's
``pkg/controller/endpointslice`` (reconciler.go): mirror each Service's
ready backend addresses into EndpointSlice objects bounded at
``max_endpoints_per_slice`` (reference default 100), named
``<service>-<index>`` and labeled ``kubernetes.io/service-name`` so
consumers (kube-proxy's EndpointSliceCache) can select them. Slices are
rewritten in place and excess slices deleted when a service shrinks.
"""

from __future__ import annotations

from kubernetes_tpu.api.types import (
    EndpointAddress,
    EndpointSlice,
    ObjectMeta,
    Pod,
    Service,
)
from kubernetes_tpu.controllers.base import Controller, split_key

SERVICE_NAME_LABEL = "kubernetes.io/service-name"


class EndpointSliceController(Controller):
    name = "endpointslice"

    max_endpoints_per_slice = 100

    def register(self) -> None:
        self.factory.informer_for("Service").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=self.enqueue,
        )
        self.factory.informer_for("Pod").add_event_handler(
            on_add=self._pod_changed,
            on_update=lambda old, new: (self._pod_changed(old),
                                        self._pod_changed(new)),
            on_delete=self._pod_changed,
        )
        self.pod_lister = self.factory.lister_for("Pod")
        self.svc_lister = self.factory.lister_for("Service")

    def _pod_changed(self, pod: Pod) -> None:
        for svc in self.svc_lister.by_namespace(pod.namespace):
            if self._selects(svc, pod):
                self.enqueue(svc)

    @staticmethod
    def _selects(svc: Service, pod: Pod) -> bool:
        if not svc.selector:
            return False
        return all(
            pod.metadata.labels.get(k) == v for k, v in svc.selector.items()
        )

    def _existing_slices(self, namespace: str, service: str):
        return [
            es for es in self.store.list_endpoint_slices()
            if es.namespace == namespace
            and es.metadata.labels.get(SERVICE_NAME_LABEL) == service
        ]

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        svc = self.store.get_object("Service", ns, name)
        existing = self._existing_slices(ns, name)
        if svc is None:
            for es in existing:
                self.store.delete_object("EndpointSlice", ns, es.name)
            return
        if not svc.selector:
            # selectorless Services manage their endpoints manually; the
            # reference controller skips them entirely
            # (endpointslice_controller.go syncService: nil-selector
            # return) — materializing an empty '<svc>-0' slice would
            # fight the manual owner. Drop any slices this controller
            # previously created for it.
            for es in existing:
                self.store.delete_object("EndpointSlice", ns, es.name)
            return
        addresses = [
            EndpointAddress(
                # same placeholder scheme as the endpoints controller
                # when no IP was allocated yet
                ip=p.status.pod_ip or p.full_name(),
                node_name=p.spec.node_name,
                target_pod=f"{p.namespace}/{p.metadata.name}",
            )
            for p in sorted(
                (p for p in self.pod_lister.by_namespace(ns)
                 if self._selects(svc, p) and p.spec.node_name
                 and p.metadata.deletion_timestamp is None),
                key=lambda p: p.metadata.name,
            )
        ]
        chunks = [
            addresses[i:i + self.max_endpoints_per_slice]
            for i in range(0, len(addresses), self.max_endpoints_per_slice)
        ] or [[]]
        wanted = {}
        for idx, chunk in enumerate(chunks):
            slice_name = f"{name}-{idx}"
            wanted[slice_name] = EndpointSlice(
                metadata=ObjectMeta(
                    name=slice_name, namespace=ns,
                    labels={SERVICE_NAME_LABEL: name},
                ),
                endpoints=chunk,
                ports=list(svc.ports),
            )
        def fingerprint(es: EndpointSlice):
            # FULL address + port identity: an IP assigned after
            # scheduling (or a changed port) must rewrite the slice,
            # not just membership changes
            return (
                [(a.ip, a.node_name, a.target_pod) for a in es.endpoints],
                [(p.name, p.port, p.target_port) for p in es.ports],
            )

        current = {es.name: es for es in existing}
        for slice_name, es in wanted.items():
            old = current.get(slice_name)
            if old is None or fingerprint(old) != fingerprint(es):
                self.store.add_endpoint_slice(es)
        for slice_name in current:
            if slice_name not in wanted:
                self.store.delete_object("EndpointSlice", ns, slice_name)
