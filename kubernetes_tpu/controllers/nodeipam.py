"""Node IPAM controller.

Behavioral equivalent of the reference's ``pkg/controller/nodeipam``
(range allocator): carve per-node pod CIDRs out of the cluster CIDR and
assign each new node one (``node.spec.podCIDR``); release the block when
the node is deleted. The default mirrors kubeadm's
``--pod-network-cidr=10.244.0.0/16`` with /24 node masks.
"""

from __future__ import annotations

import ipaddress
import threading

from kubernetes_tpu.api.types import Node, shallow_copy
from kubernetes_tpu.controllers.base import Controller


class NodeIpamController(Controller):
    name = "nodeipam"

    cluster_cidr = "10.244.0.0/16"
    node_mask = 24

    def register(self) -> None:
        # Node is cluster-scoped: key by bare name
        self.factory.informer_for("Node").add_event_handler(
            on_add=lambda n: self.enqueue_key(n.name),
            on_update=lambda old, new: self.enqueue_key(new.name),
            on_delete=self._release,
        )
        self._alloc_lock = threading.Lock()
        self._network = ipaddress.ip_network(self.cluster_cidr)
        self._subnets = self._network.subnets(
            new_prefix=self.node_mask
        )
        self._free: list = []          # released blocks, reused first
        self._in_use: dict = {}        # cidr -> node name
        self._adopted = False

    def _claim(self, node_name: str) -> str:
        with self._alloc_lock:
            # adopt pre-existing assignments exactly once (restart path)
            if not self._adopted:
                self._adopted = True
                for n in self.store.list_nodes():
                    if n.spec.pod_cidr and \
                            n.spec.pod_cidr not in self._in_use:
                        self._in_use[n.spec.pod_cidr] = n.name
            if self._free:
                cidr = self._free.pop()
            else:
                for subnet in self._subnets:
                    cidr = str(subnet)
                    if cidr not in self._in_use:
                        break
                else:
                    raise RuntimeError(
                        f"cluster CIDR {self.cluster_cidr} exhausted"
                    )
            self._in_use[cidr] = node_name
            return cidr

    def _release(self, node: Node) -> None:
        if not node.spec.pod_cidr:
            return
        with self._alloc_lock:
            if self._in_use.pop(node.spec.pod_cidr, None) is not None:
                self._free.append(node.spec.pod_cidr)

    def sync(self, key: str) -> None:
        node = self.store.get_node(key)
        if node is None or node.spec.pod_cidr:
            return
        updated = shallow_copy(node)
        updated.spec = shallow_copy(node.spec)
        updated.spec.pod_cidr = self._claim(node.name)
        self.store.update_node(updated)
