"""ServiceAccount token controller.

Behavioral equivalent of the reference's
``pkg/controller/serviceaccount/tokens_controller.go:124
NewTokensController``: every ServiceAccount carries a token Secret
(type ``kubernetes.io/service-account-token``) minted by this loop and
referenced from ``sa.secrets``; token secrets whose account is gone (or
whose recorded uid no longer matches — a deleted-and-recreated account
must not inherit the old credential) are deleted.

The apiserver's bearer authn resolves these tokens to
``system:serviceaccount:<namespace>:<name>`` identities
(``apiserver/rest.py`` ``_user`` → ``resolve_sa_token``), which is what
makes the RBAC authorizer's ServiceAccount subject arm
(``apiserver/rbac.py`` ``_subject_matches``) live end-to-end. An opaque
random token stands in for the reference's signed JWT
(``pkg/serviceaccount/jwt.go``) — the in-process store is the trust
root, so possession-of-secret is the same property the JWT signature
provides there.
"""

from __future__ import annotations

import secrets as _secrets

from kubernetes_tpu.api.types import ObjectMeta, Secret
from kubernetes_tpu.controllers.base import Controller, split_key

SA_TOKEN_TYPE = "kubernetes.io/service-account-token"
SA_NAME_ANNOTATION = "kubernetes.io/service-account.name"
SA_UID_ANNOTATION = "kubernetes.io/service-account.uid"


def sa_username(namespace: str, name: str) -> str:
    """The identity a service-account token authenticates as
    (reference ``pkg/serviceaccount/util.go`` MakeUsername)."""
    return f"system:serviceaccount:{namespace}:{name}"


class TokensController(Controller):
    name = "serviceaccount-token"

    def register(self) -> None:
        self.factory.informer_for("ServiceAccount").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
            on_delete=self.enqueue,
        )
        # a deleted token secret re-mints; an orphaned one (account gone)
        # gets cleaned up by the same sync
        self.factory.informer_for("Secret").add_event_handler(
            on_add=self._secret_changed,
            on_delete=self._secret_changed,
        )

    def _secret_changed(self, secret: Secret) -> None:
        if secret.type != SA_TOKEN_TYPE:
            return
        sa_name = secret.metadata.annotations.get(SA_NAME_ANNOTATION)
        if sa_name:
            self.enqueue_key(f"{secret.namespace}/{sa_name}")

    # ------------------------------------------------------------------
    def _token_secrets(self, namespace: str, sa_name: str):
        return [
            s for s in self.store.list_objects("Secret", namespace)
            if s.type == SA_TOKEN_TYPE
            and s.metadata.annotations.get(SA_NAME_ANNOTATION) == sa_name
        ]

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        sa = self.store.get_service_account(ns, name)
        existing = self._token_secrets(ns, name)
        if sa is None:
            # account gone: its credentials die with it
            for s in existing:
                self.store.delete_object("Secret", ns, s.name)
            return
        live = []
        for s in existing:
            if s.metadata.annotations.get(SA_UID_ANNOTATION) == \
                    sa.metadata.uid:
                live.append(s)
            else:
                # recreated account with a reused name: the old token
                # must not authenticate as the new identity
                self.store.delete_object("Secret", ns, s.name)
        if not live:
            secret_name = f"{name}-token-{_secrets.token_hex(3)}"
            self.store.create_object("Secret", Secret(
                metadata=ObjectMeta(
                    name=secret_name, namespace=ns,
                    annotations={
                        SA_NAME_ANNOTATION: name,
                        SA_UID_ANNOTATION: sa.metadata.uid,
                    },
                ),
                type=SA_TOKEN_TYPE,
                data={
                    "token": _secrets.token_urlsafe(24),
                    "namespace": ns,
                },
            ))
            live = [self.store.get_object("Secret", ns, secret_name)]
        wanted = sorted(s.name for s in live)

        def mutate(obj) -> bool:
            if obj.secrets == wanted:
                return False
            obj.secrets = wanted
            return True

        self.store.mutate_object("ServiceAccount", ns, name, mutate)
