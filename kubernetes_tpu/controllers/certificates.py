"""Certificate controllers (reference
``cmd/kube-controller-manager/app/certificates.go:38,170`` wiring
``pkg/controller/certificates/{approver,signer,cleaner}``):

- **csrapproving**: auto-approves CSRs whose signerName is one of the
  kubelet bootstrap signers (approver.go sarApprover — the subject-
  access-review step collapses to the username check here since the
  in-process identities are bootstrap-provisioned),
- **csrsigning**: issues a certificate for approved CSRs
  (signer.go). The framework's CA is an HMAC-based stand-in — the
  signing FLOW (approval condition gates issuance, certificate lands in
  status, re-issue is idempotent) is the reconciled behavior; X.509 DER
  is not load-bearing for an in-process control plane,
- **csrcleaner**: drops stale CSRs (cleaner.go: approved/denied/failed
  after 1h, pending after 24h).
"""

from __future__ import annotations

import hashlib
import hmac
import time

from kubernetes_tpu.api.types import CertificateSigningRequest, CSRCondition
from kubernetes_tpu.controllers.base import Controller

KUBELET_SERVING_SIGNER = "kubernetes.io/kubelet-serving"
KUBE_APISERVER_CLIENT_KUBELET_SIGNER = \
    "kubernetes.io/kube-apiserver-client-kubelet"
KUBE_APISERVER_CLIENT_SIGNER = "kubernetes.io/kube-apiserver-client"

AUTO_APPROVED_SIGNERS = (
    KUBELET_SERVING_SIGNER,
    KUBE_APISERVER_CLIENT_KUBELET_SIGNER,
)

# cleaner.go thresholds
APPROVED_EXPIRATION_S = 3600.0
DENIED_EXPIRATION_S = 3600.0
PENDING_EXPIRATION_S = 24 * 3600.0

CA_KEY = b"kubernetes-tpu-cluster-ca"


def sign_request(request: str, signer_name: str) -> str:
    """The stand-in CA: a deterministic PEM-shaped blob binding the
    request payload to this cluster's CA key."""
    sig = hmac.new(
        CA_KEY, f"{signer_name}:{request}".encode(), hashlib.sha256
    ).hexdigest()
    return (
        "-----BEGIN CERTIFICATE-----\n"
        f"signer: {signer_name}\n"
        f"request-digest: {hashlib.sha256(request.encode()).hexdigest()}\n"
        f"ca-signature: {sig}\n"
        "-----END CERTIFICATE-----\n"
    )


class CSRApprovingController(Controller):
    name = "csrapproving"

    def register(self) -> None:
        self.factory.informer_for("CertificateSigningRequest") \
            .add_event_handler(
                on_add=lambda c: self.enqueue_key(c.metadata.name),
                on_update=lambda o, n: self.enqueue_key(n.metadata.name),
            )

    def sync(self, key: str) -> None:
        csr = self.store.get_object("CertificateSigningRequest", "", key)
        if csr is None or csr.approved or csr.denied:
            return
        if csr.signer_name not in AUTO_APPROVED_SIGNERS:
            return
        # approver.go recognizers: kubelet client CSRs must come from a
        # bootstrap/node identity
        if not (csr.username.startswith("system:node:")
                or csr.username.startswith("system:bootstrap:")):
            return

        def mutate(c: CertificateSigningRequest) -> bool:
            if c.approved or c.denied:
                return False
            c.conditions = list(c.conditions) + [CSRCondition(
                type="Approved", reason="AutoApproved",
                message="auto-approved by csrapproving",
                timestamp=time.time(),
            )]
            return True

        self.store.mutate_object("CertificateSigningRequest", "", key,
                                 mutate)


class CSRSigningController(Controller):
    name = "csrsigning"

    def register(self) -> None:
        self.factory.informer_for("CertificateSigningRequest") \
            .add_event_handler(
                on_add=lambda c: self.enqueue_key(c.metadata.name),
                on_update=lambda o, n: self.enqueue_key(n.metadata.name),
            )

    def sync(self, key: str) -> None:
        csr = self.store.get_object("CertificateSigningRequest", "", key)
        if csr is None or not csr.approved or csr.denied or csr.certificate:
            return

        def mutate(c: CertificateSigningRequest) -> bool:
            if not c.approved or c.certificate:
                return False
            c.certificate = sign_request(c.request, c.signer_name)
            return True

        self.store.mutate_object("CertificateSigningRequest", "", key,
                                 mutate)


class CSRCleanerController(Controller):
    """cleaner.go polls every 60s; the interval is injectable so tests
    don't wait wall-clock hours (thresholds injectable likewise)."""

    name = "csrcleaner"
    RESYNC_SECONDS = 60.0

    def register(self) -> None:
        self.approved_ttl = APPROVED_EXPIRATION_S
        self.denied_ttl = DENIED_EXPIRATION_S
        self.pending_ttl = PENDING_EXPIRATION_S

    def resync(self) -> None:
        self.enqueue_key("sweep")

    def sync(self, key: str) -> None:
        now = time.time()
        for csr in self.store.list_objects("CertificateSigningRequest"):
            age = now - (csr.metadata.creation_timestamp or now)
            if csr.approved or csr.denied:
                ttl = self.approved_ttl if csr.approved else self.denied_ttl
            else:
                ttl = self.pending_ttl
            if age > ttl:
                self.store.delete_object(
                    "CertificateSigningRequest", "", csr.metadata.name
                )
