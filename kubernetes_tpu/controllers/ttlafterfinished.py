"""TTL-after-finished controller.

Behavioral equivalent of the reference's ``pkg/controller/ttlafterfinished``
(ttlafterfinished_controller.go): Jobs that declare
``ttlSecondsAfterFinished`` are deleted once the TTL has elapsed past
their completion time. Jobs not yet expired re-queue for exactly the
remaining interval (processJob's requeueAfter), so expiry needs no
polling loop.
"""

from __future__ import annotations

import time

from kubernetes_tpu.api.types import Job
from kubernetes_tpu.controllers.base import Controller, split_key


def job_finished(job: Job) -> bool:
    """Complete or Failed condition (the reference checks job
    conditions; here: all completions succeeded, or any pod failed)."""
    return (
        job.status.succeeded >= job.completions or job.status.failed > 0
    )


class TTLAfterFinishedController(Controller):
    name = "ttl-after-finished"

    def register(self) -> None:
        self.factory.informer_for("Job").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
        )

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        job = self.store.get_job(ns, name)
        if job is None or job.ttl_seconds_after_finished is None:
            return
        if not job_finished(job):
            return
        finished_at = job.status.completion_time
        if finished_at is None:
            # completion time unset: stamp it now (the job may predate
            # the ttl feature) so the TTL has an anchor. Copy-on-write —
            # store/informer-cached instances must never mutate in place
            # (watch consumers diff old vs new objects).
            from kubernetes_tpu.api.types import shallow_copy

            finished_at = time.time()
            updated = shallow_copy(job)
            updated.status = shallow_copy(job.status)
            updated.status.completion_time = finished_at
            self.store.add_job(updated)
        expires_at = finished_at + job.ttl_seconds_after_finished
        now = time.time()
        if now < expires_at:
            self.queue.add_after(key, expires_at - now)
            return
        # cascade: owned pods die with the job (the reference relies on
        # foreground GC; the garbage collector loop also covers this)
        for p in self.store.list_pods():
            if p.namespace != ns:
                continue
            if any(r.get("kind") == "Job" and r.get("name") == name
                   for r in p.metadata.owner_references):
                self.store.delete_pod(p.namespace, p.name)
        self.store.delete_job(ns, name)
