"""ResourceQuota controller.

Behavioral equivalent of the reference's ``pkg/controller/resourcequota``
(resource_quota_controller.go syncResourceQuota): recompute
``status.used`` for each quota from the live objects in its namespace —
pod count and aggregate container resource requests — and publish the
updated status. Enforcement happens at admission (the ``ResourceQuota``
admission plugin consults the live status), exactly as upstream splits
controller (accounting) from admission (gatekeeping).

Usage keys mirror the upstream evaluator: ``pods``, ``requests.cpu``,
``requests.memory`` (``cpu``/``memory`` accepted as aliases).
"""

from __future__ import annotations

from kubernetes_tpu.api.resource import Quantity, parse_quantity
from kubernetes_tpu.api.types import (
    FAILED,
    SUCCEEDED,
    Pod,
    ResourceQuota,
    shallow_copy,
)
from kubernetes_tpu.controllers.base import Controller, split_key


def compute_usage(pods) -> dict:
    """Aggregate quota usage over non-terminal pods (the reference's
    core evaluator excludes Succeeded/Failed pods)."""
    n = 0
    cpu_milli = 0
    mem = 0
    for p in pods:
        if p.status.phase in (SUCCEEDED, FAILED):
            continue
        n += 1
        for c in p.spec.containers:
            req = c.resources.requests
            if "cpu" in req:
                cpu_milli += int(req["cpu"].milli_value())
            if "memory" in req:
                mem += int(req["memory"].value())
    return {
        "pods": parse_quantity(str(n)),
        "requests.cpu": Quantity.from_milli(cpu_milli),
        "requests.memory": parse_quantity(str(mem)),
    }


class ResourceQuotaController(Controller):
    name = "resourcequota"

    def register(self) -> None:
        self.factory.informer_for("ResourceQuota").add_event_handler(
            on_add=self.enqueue,
            on_update=lambda old, new: self.enqueue(new),
        )
        self.factory.informer_for("Pod").add_event_handler(
            on_add=self._pod_changed,
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_changed,
        )
        self.pod_lister = self.factory.lister_for("Pod")

    def _pod_changed(self, pod: Pod) -> None:
        for q in self.store.list_resource_quotas():
            if q.namespace == pod.namespace:
                self.enqueue(q)

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        quota = self.store.get_resource_quota(ns, name)
        if quota is None:
            return
        usage = compute_usage(self.pod_lister.by_namespace(ns))
        used = {k: usage[k] for k in usage if k in quota.hard}
        # aliases: hard may say cpu/memory instead of requests.*
        for alias, full in (("cpu", "requests.cpu"),
                            ("memory", "requests.memory")):
            if alias in quota.hard:
                used[alias] = usage[full]
        if {k: str(v) for k, v in used.items()} == \
                {k: str(v) for k, v in quota.used.items()}:
            return
        updated = shallow_copy(quota)
        updated.used = used
        self.store.update_object("ResourceQuota", updated)
