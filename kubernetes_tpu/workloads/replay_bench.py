"""Replay bench rows + chaos cells: the scenario families measured
through the REAL fabric, judged by SLO verdicts and invariants.

``run_replay_row`` runs one family OPEN-LOOP over the REST fabric
(apiserver child process with WAL/RBAC/APF, arrivals through
authenticated clients, scheduler fed by watch streams) and emits a
BENCH-JSON row whose headline is **arrival→bind latency** — per-pod
schedule latency measured from the arrival instant, the number a
submitting user experiences — next to rate-normalized throughput, the
family's hard invariants, PR 8's SLO verdicts, and the
``replay[...]`` diag segment. Family extras:

- ``gangs`` runs TWO arms — MeshLocality scored vs adjacency-blind —
  and the row carries the adjacency A/B (scored must beat blind);
- ``tenancy`` runs the PR 4 autoscaler (node-group capacity bought
  mid-trace) and PR 6 APF together: each tenant's arrivals ride its
  own authenticated client, so serve and batch are separate fair-
  queued flows; the row splits arrival→bind latency per class;
- ``storm`` reports the preemption ledger and the
  no-priority-inversion-at-quiesce verdict.

``run_replay_cell`` is the chaos-matrix face (``--suite replay``):
store-direct mini-replays per (family × seed) asserting the
invariants — zero lost pods, gang atomicity, no priority inversion.
"""

from __future__ import annotations

import math
import multiprocessing as mp
import tempfile
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.workloads.scenarios import (
    REPLAY_FAMILIES,
    TENANCY_NODE_CPU,
    FamilySpec,
    _tenancy_sizing,
)
from kubernetes_tpu.workloads.trace import Trace

SCHEDULER_TOKEN = "replay-scheduler-token"
CREATOR_TOKEN = "replay-creator-token"
SERVE_LATENCY_BUDGET_S = 2.0


def tenant_tokens(spec: FamilySpec) -> Dict[str, str]:
    return {f"{t}-token": t for t in spec.tenants}


# ---------------------------------------------------------------------------
# apiserver child (spawned; must stay jax-free — see harness/__init__)


def _apiserver_main(conn, wal_dir: Optional[str],
                    extra_tokens: Optional[dict] = None) -> None:
    """Like the REST harness's apiserver child, but replay tenants get
    a role that can SUBMIT workloads (create/delete pods) — the
    tenancy family's tenants are real users of the fabric, not
    read-only aggressors."""
    from kubernetes_tpu.apiserver.rbac import provision_bootstrap_policy
    from kubernetes_tpu.apiserver.rest import APIServer
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.apiserver.wal import attach_wal

    from kubernetes_tpu.utils.gctune import tune_for_throughput

    tune_for_throughput()
    store = ClusterStore()
    wal = attach_wal(store, wal_dir, snapshot_every=200_000,
                     async_serialize=True) if wal_dir else None
    authz = provision_bootstrap_policy(store)
    authz.add_user_to_group("replay-creator", "system:masters")
    tokens = {SCHEDULER_TOKEN: "system:kube-scheduler",
              CREATOR_TOKEN: "replay-creator"}
    tokens.update(extra_tokens or {})
    if extra_tokens:
        from kubernetes_tpu.api.types import (
            ClusterRole, ClusterRoleBinding, ObjectMeta, PolicyRule,
            RBACSubject, RoleRef,
        )

        store.add_cluster_role(ClusterRole(
            metadata=ObjectMeta(name="replay-tenant"),
            rules=[PolicyRule(
                verbs=["get", "list", "watch", "create", "delete"],
                resources=["pods"])]))
        store.add_cluster_role_binding(ClusterRoleBinding(
            metadata=ObjectMeta(name="replay-tenants"),
            subjects=[RBACSubject(kind="User", name=u)
                      for u in extra_tokens.values()],
            role_ref=RoleRef(kind="ClusterRole", name="replay-tenant")))
    server = APIServer(store=store, authorizer=authz,
                       tokens=tokens).start()
    conn.send(server.url)
    while True:
        msg = conn.recv()
        if msg == "stop":
            break
        if msg == "counts":
            pods = store.list_pods()
            if wal is not None:
                wal.drain()
            conn.send({
                "pods_total": len(pods),
                "pods_bound": sum(1 for p in pods if p.spec.node_name),
            })
    server.shutdown_server()
    if wal is not None:
        wal.close()
    conn.send("stopped")


# ---------------------------------------------------------------------------
# one replay run (store-direct or REST)


def _pump_to_quiesce(sched, bs, engine, deadline: float,
                     settle_s: float = 1.0) -> None:
    """Drive the scheduler until the replay is over: trace exhausted,
    due expiries delivered, queues drained, and no progress for
    ``settle_s`` (deletions re-activate parked pods, so 'drained' must
    hold for a settle window, not an instant)."""
    quiet_since = None
    while time.monotonic() < deadline:
        sched.queue.flush_backoff_completed()
        progressed = bs.run_batch(pop_timeout=0.01) if bs is not None \
            else sched.schedule_one(pop_timeout=0.01)
        now = time.monotonic()
        if progressed:
            quiet_since = None
            continue
        busy = (not engine.injection_done.is_set()
                or engine.due_expiries() > 0
                or sched.queue.pending_active_count() > 0)
        if busy:
            quiet_since = None
        elif quiet_since is None:
            quiet_since = now
        elif now - quiet_since >= settle_s:
            return
        time.sleep(0.005)
    raise TimeoutError("replay did not quiesce before deadline")


def run_replay_once(
    family: str,
    seed: int = 11,
    scale: float = 1.0,
    time_scale: float = 1.0,
    *,
    rest: bool = False,
    use_batch: bool = True,
    max_batch: int = 1024,
    qps: Optional[float] = 5000.0,
    wait_timeout: float = 600.0,
    scored: bool = True,
    expire: bool = True,
    autoscale: Optional[bool] = None,
    trace: Optional[Trace] = None,
    progress: Optional[Callable[[str], None]] = None,
):
    """One replay run. Returns ``(stats, extras)`` where ``extras``
    carries the observability sub-objects (telemetry/freshness),
    server truth for REST runs, and autoscaler/apf ledgers when those
    layers were active. ``scored=False`` is the adjacency-blind arm."""
    from kubernetes_tpu.api.types import Node
    from kubernetes_tpu.config.feature_gates import FeatureGates
    from kubernetes_tpu.harness.perf import (
        attach_slo_baseline,
        collect_freshness,
        reset_sli_window,
    )
    from kubernetes_tpu.observability import get_tracer
    from kubernetes_tpu.observability.devprof import get_devprof
    from kubernetes_tpu.observability.slo import get_slo_engine
    from kubernetes_tpu.scheduler.framework.plugins import mesh_locality
    from kubernetes_tpu.scheduler.scheduler import Scheduler
    from kubernetes_tpu.sidecar import attach_batch_scheduler
    from kubernetes_tpu.utils.gctune import tune_for_throughput
    from kubernetes_tpu.workloads.replay import ReplayEngine

    spec = REPLAY_FAMILIES[family]
    if autoscale is None:
        autoscale = spec.autoscale
    if trace is None:
        trace = spec.build(seed, scale)
    tune_for_throughput()
    get_tracer().clear()
    get_devprof().reset(workload=f"replay/{family}")
    reset_sli_window()
    prev_scored = mesh_locality.enabled()
    mesh_locality.configure(scored)

    extras: Dict = {"family": family, "seed": seed, "scale": scale}
    ctx = api_conn = api_proc = None
    wal_dir = None
    clients: List = []
    ca = factory = None
    engine = None
    sched = None
    slo_engine = get_slo_engine()
    try:
        if rest:
            from kubernetes_tpu.client.restcluster import (
                RestClusterClient,
            )

            ctx = mp.get_context("spawn")
            wal_dir = tempfile.mkdtemp(prefix="ktpu-replay-wal-")
            api_conn, api_child = ctx.Pipe()
            api_proc = ctx.Process(
                target=_apiserver_main,
                args=(api_child, wal_dir, tenant_tokens(spec)),
                daemon=True)
            api_proc.start()
            url = api_conn.recv()
            client = RestClusterClient(url, token=SCHEDULER_TOKEN,
                                       qps=qps)
            event_client = RestClusterClient(url, token=SCHEDULER_TOKEN,
                                             qps=qps)
            creator = RestClusterClient(url, token=CREATOR_TOKEN,
                                        qps=qps)
            clients = [client, event_client, creator]
            tenant_clients = {}
            for tenant, token in ((t, f"{t}-token")
                                  for t in spec.tenants):
                # tenants ride the public JSON wire: the binary codec
                # (pickle) is gated to trusted control-plane
                # identities, and an untrusted tenant speaking JSON is
                # also the honest multi-tenant wire shape
                c = RestClusterClient(url, token=token, qps=qps,
                                      binary=False)
                tenant_clients[tenant] = c
                clients.append(c)
            target, sched_client = creator, client
        else:
            from kubernetes_tpu.apiserver.store import ClusterStore

            store = ClusterStore()
            target = sched_client = store
            event_client = None
            tenant_clients = {}

        # -- node fleet (node-group-owned when the autoscaler plays) --
        if autoscale:
            from kubernetes_tpu.autoscaler import (
                NodeGroup,
                NodeGroupRegistry,
            )

            n_serve, n_batch, initial = _tenancy_sizing(scale)
            need = max(initial + 1, math.ceil(
                initial / 0.45))
            registry = NodeGroupRegistry()
            group = registry.add(NodeGroup(
                "ng-replay", cpu=str(TENANCY_NODE_CPU), memory="32Gi",
                min_size=initial, max_size=need + 4,
                boot_latency=0.4))
            initial_nodes = [group.node_template(i)
                             for i in range(initial)]
        else:
            registry = None
            initial_nodes = [Node.from_dict(d)
                             for d in spec.node_specs(scale)]
        if rest:
            target.create_objects_bulk("Node", initial_nodes)
        else:
            for n in initial_nodes:
                target.add_node(n)

        # -- scheduler (always the gang provider: every family's gang
        #    semantics ride the coscheduling machinery) --
        gates = FeatureGates({"TPUBatchScheduler": use_batch})
        sched = Scheduler.create(
            sched_client, feature_gates=gates,
            provider="GangSchedulingProvider",
            event_client=event_client)
        bs = attach_batch_scheduler(sched, max_batch=max_batch) \
            if use_batch else None
        attach_slo_baseline(sched)
        if rest and slo_engine.enabled:
            slo_engine.start(interval_s=1.0)
        sched.start()
        if rest:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and \
                    sched.cache.node_count() < len(initial_nodes):
                time.sleep(0.02)
        if bs is not None:
            from kubernetes_tpu.workloads.trace import events_to_pods

            samples = events_to_pods(trace.events[:128])
            warm = bs.warmup(sample_pods=samples) if samples else 0.0
            if progress and warm > 0.05:
                progress(f"replay/{family}: solver warmup {warm:.1f}s")

        # -- autoscaler (the tenancy family's capacity acquisition) --
        if autoscale:
            from kubernetes_tpu.autoscaler import ClusterAutoscaler
            from kubernetes_tpu.client.informers import (
                SharedInformerFactory,
            )

            ca_client = target   # masters identity over REST; store
            factory = SharedInformerFactory(ca_client)
            ca = ClusterAutoscaler(ca_client, factory,
                                   registry=registry)
            ca.RESYNC_SECONDS = 0.2
            ca.scale_up_cooldown = 0.5
            ca.max_virtual_per_group = 128
            ca.scale_down_enabled = False
            ca.queue_introspect = sched.queue
            factory.start()
            factory.wait_for_cache_sync()
            ca.run()

        # -- the replay itself --
        engine = ReplayEngine(
            target, trace, time_scale=time_scale, expire=expire,
            tenant_targets=tenant_clients or None, progress=progress)
        t0 = time.monotonic()
        engine.start()
        _pump_to_quiesce(sched, bs, engine,
                         time.monotonic() + wait_timeout)
        if bs is not None:
            bs.flush()
        sched.wait_for_inflight_bindings(timeout=30.0)
        extras["wall_s"] = round(time.monotonic() - t0, 2)
        stats = engine.finish()
        engine = None

        # -- observability collection --
        if rest:
            from kubernetes_tpu.metrics import default_registry
            from kubernetes_tpu.metrics.federation import (
                metrics_federation,
            )

            fed = metrics_federation()
            fed.forget_instance("apiserver")
            fed.forget_instance("scheduler")
            try:
                fed.scrape(url, instance="apiserver",
                           token=SCHEDULER_TOKEN, fold=True)
            except Exception:  # noqa: BLE001 — best-effort
                pass
            fed.absorb_registry(default_registry(),
                                instance="scheduler")
            extras["federation_instances"] = sorted(fed.instances())
            try:
                code, snap = client._request("GET", "/debug/apf")
                if code == 200 and isinstance(snap, dict):
                    rejected = sum(
                        sum((lv.get("rejected") or {}).values())
                        for lv in (snap.get("levels") or {}).values())
                    extras["apf"] = {"rejections": rejected}
            except Exception:  # noqa: BLE001
                pass
        if ca is not None:
            extras["autoscaler"] = {
                "scaleup_decisions": ca.scale_up_events,
                "nodes_provisioned": ca.provisioner.provisioned_total,
                "nodes_end": len(target.list_nodes()),
            }
        dp = get_devprof()
        extras["telemetry"] = dp.summary() if dp.enabled else {}
        extras["freshness"] = collect_freshness(extras["telemetry"])
        extras["p99_e2e_ms"] = round(
            sched.metrics.e2e_scheduling_duration.quantile(
                0.99, "scheduled") * 1000, 1)
        if rest:
            try:
                api_conn.send("counts")
                extras["server"] = api_conn.recv()
            except (OSError, EOFError):
                pass
        return stats, extras
    finally:
        mesh_locality.configure(prev_scored)
        if engine is not None:
            try:
                engine.finish()
            except Exception:  # noqa: BLE001
                pass
        if ca is not None:
            ca.stop()
        if factory is not None:
            factory.stop()
        if rest and slo_engine.enabled:
            slo_engine.stop()
        if sched is not None:
            sched.stop()
        for c in clients:
            stop = getattr(c, "close", None)
            if stop is not None:
                try:
                    stop()
                except Exception:  # noqa: BLE001
                    pass
        if api_conn is not None:
            try:
                api_conn.send("stop")
                if api_conn.poll(5.0):
                    api_conn.recv()
            except (OSError, EOFError, BrokenPipeError):
                pass
            api_proc.join(timeout=5.0)
            if api_proc.is_alive():
                api_proc.terminate()
        if wal_dir:
            import shutil

            shutil.rmtree(wal_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# family verdicts


def family_verdicts(spec: FamilySpec, stats,
                    serve_budget_s: float = SERVE_LATENCY_BUDGET_S
                    ) -> Dict[str, bool]:
    out: Dict[str, bool] = {}
    for check in spec.checks:
        if check == "lost":
            # zero-lost covers the whole pipeline: every trace event
            # was actually injected (no swallowed send failures) and
            # every injected pod is accounted at quiesce
            out["zero_lost_pods"] = (
                stats.lost == 0
                and stats.injected == stats.expected
                and not stats.send_errors)
        elif check == "inversion":
            out["no_priority_inversion"] = \
                stats.priority_inversions == 0
        elif check == "gangs":
            out["gang_atomicity"] = stats.gangs_partial == 0
        elif check == "serve_latency":
            # a run where no serve pod ever bound must FAIL, not pass
            # vacuously with a defaulted 0.0 p99 (e.g. a wedged
            # autoscaler leaving the whole serve class pending)
            lat = stats.arrival_to_bind.get("serve") or {}
            out["serve_p99_within_budget"] = (
                lat.get("count", 0) > 0
                and lat.get("p99", 0.0) <= serve_budget_s)
        # "adjacency" is judged at the A/B level (needs both arms)
    return out


def _replay_diag(stats) -> None:
    import sys

    from kubernetes_tpu.harness import diagfmt

    seg = diagfmt.format_replay({
        "family": stats.family,
        "rate": stats.offered_rate,
        "p99_arrival_to_bind_ms": stats.latency_p99_ms(),
        "preempted": stats.preempted,
        "gangs_intact": stats.gangs_partial == 0,
        "lost": stats.lost,
        "expired": stats.expired,
        "inversions": stats.priority_inversions,
    })
    print(diagfmt.format_diag([seg]), file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# the bench row


def run_replay_row(
    family: str,
    seed: int = 11,
    scale: float = 1.0,
    time_scale: float = 1.0,
    *,
    rest: bool = True,
    max_batch: int = 1024,
    qps: Optional[float] = 5000.0,
    wait_timeout: float = 900.0,
    progress: Optional[Callable[[str], None]] = None,
) -> dict:
    """One committed replay bench row (``bench.py --config
    replay:<family>``). The gang family runs scored + adjacency-blind
    arms; the row's verdicts are the family invariants PLUS the SLO
    verdicts from PR 8's engine."""
    spec = REPLAY_FAMILIES[family]
    trace = spec.build(seed, scale)

    def note(msg: str) -> None:
        if progress:
            progress(f"[replay:{family}] {msg}")

    note(f"{len(trace.events)} arrivals over "
         f"{trace.duration_s * time_scale:.0f}s "
         f"(offered {trace.offered_rate / max(time_scale, 1e-9):.1f} "
         f"pods/s), seed {seed}, "
         f"{'REST fabric' if rest else 'store-direct'}")
    stats, extras = run_replay_once(
        family, seed, scale, time_scale, rest=rest,
        max_batch=max_batch, qps=qps, wait_timeout=wait_timeout,
        trace=trace, progress=progress)
    _replay_diag(stats)
    verdicts = family_verdicts(spec, stats)
    offered = stats.offered_rate
    value = (stats.ever_bound / stats.last_bind_s
             if stats.last_bind_s > 0 else 0.0)
    n_nodes = len(spec.node_specs(scale))
    row = {
        "metric": (
            f"replay_{family}[{spec.title}, {n_nodes}nodes/"
            f"{len(trace.events)}pods offered "
            f"{offered:.1f}/s seed={seed}, "
            f"{'REST fabric' if rest else 'store-direct'} open-loop]"),
        "value": round(value, 1),
        "unit": "pods/s",
        "offered_rate_pods_per_sec": round(offered, 2),
        "rate_normalized_throughput": round(
            value / offered, 3) if offered > 0 else 0.0,
        "p99_arrival_to_bind_ms": round(stats.latency_p99_ms()),
        "p50_arrival_to_bind_ms": round(
            stats.arrival_to_bind.get("all", {}).get("p50", 0.0)
            * 1000),
        "injected": stats.injected,
        "ever_bound": stats.ever_bound,
        "expired": stats.expired,
        "preempted": stats.preempted,
        "pending_at_end": stats.pending_at_end,
        "lost_pods": stats.lost,
        "priority_inversions": stats.priority_inversions,
        "gangs": {"total": stats.gangs_total,
                  "placed": stats.gangs_placed,
                  "partial": stats.gangs_partial},
        "latency_by_class_ms": {
            cls: {"p50": round(v.get("p50", 0.0) * 1000),
                  "p99": round(v.get("p99", 0.0) * 1000)}
            for cls, v in stats.arrival_to_bind.items()
            if cls != "all"},
        "invariants": verdicts,
        "invariants_ok": all(verdicts.values()),
    }
    fresh = extras.get("freshness") or {}
    if fresh:
        row["freshness"] = fresh
        slo = fresh.get("slo") or {}
        gated = {n: v for n, v in slo.items()
                 if n not in spec.slo_exempt}
        row["slo_verdicts_ok"] = (
            all(v == "ok" for v in gated.values()) if gated else None)
        row["slo_gated"] = sorted(gated)
    if extras.get("telemetry"):
        row["telemetry"] = extras["telemetry"]
    for key in ("federation_instances", "autoscaler", "apf", "server"):
        if extras.get(key):
            row[key] = extras[key]
    if family == "gangs":
        note("adjacency-blind baseline arm")
        blind_stats, _blind_extras = run_replay_once(
            family, seed, scale, time_scale, rest=rest,
            max_batch=max_batch, qps=qps, wait_timeout=wait_timeout,
            trace=trace, scored=False, progress=progress)
        _replay_diag(blind_stats)
        scored_adj = stats.mean_gang_adjacency
        blind_adj = blind_stats.mean_gang_adjacency
        row["adjacency_ab"] = {
            "scored_mean_gang_adjacency": round(scored_adj, 3)
            if scored_adj is not None else None,
            "blind_mean_gang_adjacency": round(blind_adj, 3)
            if blind_adj is not None else None,
            "scored_beats_blind": (
                scored_adj is not None and blind_adj is not None
                and scored_adj < blind_adj),
        }
        # the A/B verdict joins the invariants DICT (not just the
        # rolled-up bool): perf_report names failed invariants from
        # the dict, so the two must never disagree
        row["invariants"]["adjacency_scored_beats_blind"] = \
            row["adjacency_ab"]["scored_beats_blind"]
        row["invariants_ok"] = all(row["invariants"].values())
    note(f"{stats.ever_bound}/{stats.injected} bound, p99 "
         f"arrival→bind {row['p99_arrival_to_bind_ms']}ms, "
         f"preempted {stats.preempted}, lost {stats.lost}, "
         f"invariants_ok {row['invariants_ok']}")
    return row


# ---------------------------------------------------------------------------
# chaos cell (tools/chaos_matrix.py --suite replay)


def run_replay_cell(
    seed: int,
    family: str = "storm",
    nodes: int = 0,
    pods: int = 120,
    wait_timeout: float = 180.0,
    progress: Optional[Callable[[str], None]] = None,
) -> dict:
    """One (family × seed) chaos cell: a compressed store-direct
    mini-replay asserting the family invariants — zero lost pods, gang
    atomicity (never a partially-placed gang), no priority inversion
    at quiesce. Cell size comes from the family scale knob — the
    LARGER of the two requests wins (``pods`` relative to the ~1200-pod
    full-scale traces, ``nodes`` relative to the ~120-node storm
    fleet); the family's own node/pod ratio is part of its shape, so
    the knobs steer scale rather than set exact counts."""
    scale = min(1.0, max(0.05, pods / 1200.0, nodes / 120.0))
    spec = REPLAY_FAMILIES[family]
    stats, _extras = run_replay_once(
        family, seed, scale, time_scale=0.2, rest=False,
        max_batch=256, wait_timeout=wait_timeout, progress=progress)
    verdicts = family_verdicts(spec, stats)
    ok = all(verdicts.values())
    failures = [k for k, v in verdicts.items() if not v]
    return {
        "seed": seed,
        "profile": family,
        "ok": ok,
        "failure": ", ".join(failures),
        "stats": {
            "injected": stats.injected,
            "ever_bound": stats.ever_bound,
            "expired": stats.expired,
            "preempted": stats.preempted,
            "lost": stats.lost,
            "gangs_partial": stats.gangs_partial,
            "inversions": stats.priority_inversions,
            "p99_arrival_to_bind_ms": round(stats.latency_p99_ms()),
        },
    }
