"""Trace-replay workload engine (ROADMAP "trace-replay workload engine
+ hard scenario families").

Every committed bench row before this subsystem measured the same
shape: a synthetic, uniform, PRE-CREATED burst — the one workload a
production cluster never sees. This package models a cluster trace
(arrival process, pod-lifetime distributions, heavy-tailed resource
sizes, priority/tenant mix) as a seeded deterministic generator plus a
JSONL loader, and replays it OPEN-LOOP: pods arrive on a clock,
lifetimes expire into deletions so the scheduler faces sustained
churn, and per-pod schedule latency is measured from ARRIVAL — the
number a user submitting one pod experiences, not the batch-amortized
throughput figure.

Lazy exports (PEP 562, same contract as ``harness/__init__``): the
trace/replay/scenario layers are jax-free by design — REST-harness
child processes import them — while the bench-row harness
(``replay_bench``) transitively pulls the solver and must only load in
the parent.
"""

from kubernetes_tpu.workloads.trace import (
    Trace,
    TraceEvent,
    generate_trace,
    load_trace_jsonl,
    write_trace_jsonl,
)
from kubernetes_tpu.workloads.scenarios import (
    REPLAY_FAMILIES,
    build_family,
)

__all__ = [
    "Trace", "TraceEvent", "generate_trace",
    "load_trace_jsonl", "write_trace_jsonl",
    "REPLAY_FAMILIES", "build_family",
    "ReplayEngine", "ReplayStats",
    "run_replay_row", "run_replay_cell", "run_replay_once",
]


def __getattr__(name):
    if name in ("ReplayEngine", "ReplayStats"):
        from kubernetes_tpu.workloads import replay

        return getattr(replay, name)
    if name in ("run_replay_row", "run_replay_cell",
                "run_replay_once"):
        # lazy: replay_bench transitively imports the jax solver
        from kubernetes_tpu.workloads import replay_bench

        return getattr(replay_bench, name)
    raise AttributeError(name)
