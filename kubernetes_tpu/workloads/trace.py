"""Cluster-trace model: events, heavy-tailed distributions, a seeded
deterministic generator, and the JSONL interchange format.

A trace is an arrival-ordered sequence of :class:`TraceEvent` — one
per pod — carrying everything the replay engine needs to recreate the
pod at its arrival instant: offset from trace start, resource request,
lifetime, priority, tenant, and optional gang membership. The shapes
come from the published cluster-trace literature rather than uniform
synthetics:

- **arrivals** are a Poisson process with optional burst epochs
  (exponential inter-arrival gaps; production arrival processes are
  bursty-Poisson, not paced);
- **resource sizes** are bounded-Pareto heavy-tailed (the Azure/Google
  cluster-trace shape: most requests small, a thin tail of huge ones).
  Heavy tails are exactly what stresses the padded-shape-bucket
  discipline — every novel size histogram risks a recompile;
- **lifetimes** are a two-mode lognormal mixture (many short-lived
  tasks, a minority of long-running services), so replay produces
  sustained churn instead of a monotone fill.

Determinism contract (asserted in tier-1): ``generate_trace`` is a
pure function of ``(seed, parameters)`` — same seed + parameters →
bit-identical event sequence — and the JSONL round-trip is exact
(``load_trace_jsonl(write_trace_jsonl(t)) == t``). Only
``random.Random`` is used (Mersenne Twister + documented-stable
variates); no wall clock, no iteration-order hazards.

jax-free by design: REST-harness child processes import this module.
"""

from __future__ import annotations

import json
import math
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterable, List, Optional
from random import Random


# ---------------------------------------------------------------------------
# distribution primitives (seeded, deterministic)


def bounded_pareto(rng: Random, alpha: float, lo: float, hi: float) -> float:
    """Bounded Pareto via inverse-CDF: heavy-tailed on [lo, hi]. The
    cluster-trace resource-size shape — P(X > x) ~ x^-alpha with the
    tail truncated at ``hi`` so one sample cannot exceed any node."""
    if hi <= lo:
        return lo
    u = rng.random()
    la, ha = lo ** alpha, hi ** alpha
    return (-(u * ha - u * la - ha) / (ha * la)) ** (-1.0 / alpha)


def lognormal_mixture(rng: Random, modes) -> float:
    """Sample from a weighted mixture of lognormals: ``modes`` is a
    sequence of (weight, mu, sigma). The pod-lifetime shape: a heavy
    short-task mode plus a thin long-service mode."""
    total = sum(w for w, _, _ in modes)
    pick = rng.random() * total
    acc = 0.0
    for w, mu, sigma in modes:
        acc += w
        if pick <= acc:
            return rng.lognormvariate(mu, sigma)
    return rng.lognormvariate(modes[-1][1], modes[-1][2])


def arrivals_exactly(rng: Random, count: int, span_s: float,
                     burst_factor: float = 1.0,
                     burst_period_s: float = 0.0) -> List[float]:
    """EXACTLY ``count`` sorted arrival offsets on [0, span_s): a
    Poisson(-burst) draw at the matching mean rate, trimmed or padded
    with uniform draws to pin the count (rows and invariants key on
    it). ONE implementation — the generic generator and every scenario
    family share it, so the per-seed determinism contract has a single
    rng-call sequence to preserve."""
    rate = count / span_s if span_s > 0 else float(count)
    ts = poisson_arrivals(rng, rate, span_s, burst_factor=burst_factor,
                          burst_period_s=burst_period_s)
    while len(ts) < count:
        ts.append(rng.random() * span_s)
    return sorted(ts[:count])


def poisson_arrivals(rng: Random, rate: float, duration_s: float,
                     burst_factor: float = 1.0,
                     burst_period_s: float = 0.0) -> List[float]:
    """Arrival offsets on [0, duration_s): exponential gaps at ``rate``
    arrivals/s, optionally modulated by burst epochs — during the first
    half of every ``burst_period_s`` window the instantaneous rate is
    ``burst_factor``× the trough rate (mean held at ``rate``)."""
    out: List[float] = []
    t = 0.0
    while True:
        if burst_period_s > 0 and burst_factor > 1.0:
            phase = math.fmod(t, burst_period_s)
            # two-level square wave with mean == rate
            hi = 2.0 * rate * burst_factor / (burst_factor + 1.0)
            lo = 2.0 * rate / (burst_factor + 1.0)
            r = hi if phase < burst_period_s / 2.0 else lo
        else:
            r = rate
        t += rng.expovariate(r)
        if t >= duration_s:
            return out
        out.append(t)


# ---------------------------------------------------------------------------
# events + trace


@dataclass
class TraceEvent:
    """One pod arrival. ``t`` is the offset (seconds) from trace start;
    ``lifetime_s`` is how long the pod runs AFTER binding before the
    replay engine expires it into a deletion (None = runs forever);
    ``gang``/``gang_size`` declare coscheduling membership (the
    ``pod-group.scheduling.k8s.io`` labels are stamped into the pod
    manifest);
    ``tenant`` names the submitting identity (APF flow separation);
    ``cls`` tags the workload class (``serve``/``batch``/``filler``/
    ``gang`` — scenario families use it for per-class latency splits)."""

    t: float
    name: str
    cpu_milli: int
    memory_mib: int
    priority: int = 0
    lifetime_s: Optional[float] = None
    tenant: str = ""
    cls: str = ""
    gang: str = ""
    gang_size: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    namespace: str = "default"

    def to_dict(self) -> dict:
        d = asdict(self)
        # drop defaults for a compact, diff-stable JSONL line
        for k, default in (("priority", 0), ("lifetime_s", None),
                           ("tenant", ""), ("cls", ""), ("gang", ""),
                           ("gang_size", 0), ("labels", {}),
                           ("namespace", "default")):
            if d[k] == default:
                del d[k]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TraceEvent":
        return cls(
            t=float(d["t"]), name=d["name"],
            cpu_milli=int(d["cpu_milli"]),
            memory_mib=int(d["memory_mib"]),
            priority=int(d.get("priority", 0)),
            lifetime_s=d.get("lifetime_s"),
            tenant=d.get("tenant", ""),
            cls=d.get("cls", ""),
            gang=d.get("gang", ""),
            gang_size=int(d.get("gang_size", 0)),
            labels=dict(d.get("labels", {})),
            namespace=d.get("namespace", "default"),
        )

    def pod_dict(self) -> dict:
        """The Pod manifest for this arrival (same shape every bench
        workload builds on: one container, cpu/memory requests)."""
        labels = dict(self.labels)
        if self.gang and self.gang_size > 1:
            labels.setdefault("pod-group.scheduling.k8s.io/name",
                              self.gang)
            labels.setdefault("pod-group.scheduling.k8s.io/min-available",
                              str(self.gang_size))
        spec: dict = {
            "containers": [
                {"name": "c", "image": "registry/fake:1",
                 "resources": {"requests": {
                     "cpu": f"{self.cpu_milli}m",
                     "memory": f"{self.memory_mib}Mi"}}}
            ],
        }
        if self.priority:
            spec["priority"] = self.priority
        return {
            "metadata": {"name": self.name,
                         "namespace": self.namespace,
                         "labels": labels},
            "spec": spec,
        }


@dataclass
class Trace:
    """An arrival-ordered event sequence plus its provenance: the
    family/seed it was generated from and the offered-load summary the
    bench row and perf_report normalize against. Equality is the
    dataclass field-wise compare — the determinism contract's
    'identical trace' IS this."""

    events: List[TraceEvent]
    family: str = ""
    seed: int = 0
    duration_s: float = 0.0

    @property
    def offered_rate(self) -> float:
        """Mean offered arrival rate (pods/s) over the trace span —
        the open-loop pacing a replay row's throughput must be
        normalized by before trend comparison."""
        if not self.events:
            return 0.0
        span = self.duration_s or max(e.t for e in self.events) or 1.0
        return len(self.events) / span if span > 0 else 0.0


# ---------------------------------------------------------------------------
# generic generator (the scenario families specialize on top of this)


def generate_trace(
    seed: int,
    count: int,
    duration_s: float,
    *,
    family: str = "generic",
    name_prefix: str = "tr-",
    cpu_alpha: float = 1.5,
    cpu_lo: int = 100,
    cpu_hi: int = 4000,
    mem_per_cpu_mib: float = 1.0,
    lifetime_modes=((0.8, math.log(8.0), 0.8),
                    (0.2, math.log(120.0), 0.6)),
    priorities=((1.0, 0),),
    tenants=("",),
    burst_factor: float = 3.0,
    burst_period_s: float = 10.0,
    namespace: str = "default",
) -> Trace:
    """Seeded deterministic generator: ``count`` arrivals over
    ``duration_s`` with Poisson-burst arrivals, bounded-Pareto cpu
    sizes (memory proportional with jitter), lognormal-mixture
    lifetimes, and a weighted priority mix. Tenants round-robin.

    Same (seed, parameters) → bit-identical trace; asserted in tier-1
    (tests/test_replay.py)."""
    rng = Random(seed)
    offsets = arrivals_exactly(rng, count, duration_s,
                               burst_factor=burst_factor,
                               burst_period_s=burst_period_s)
    prio_total = sum(w for w, _ in priorities)
    events: List[TraceEvent] = []
    for i, t in enumerate(offsets):
        cpu = int(bounded_pareto(rng, cpu_alpha, cpu_lo, cpu_hi))
        mem = max(64, int(cpu * mem_per_cpu_mib
                          * rng.uniform(0.75, 1.25)))
        pick = rng.random() * prio_total
        acc, prio = 0.0, priorities[-1][1]
        for w, p in priorities:
            acc += w
            if pick <= acc:
                prio = p
                break
        life = lognormal_mixture(rng, lifetime_modes) \
            if lifetime_modes else None
        events.append(TraceEvent(
            t=round(t, 6), name=f"{name_prefix}{i}",
            cpu_milli=cpu, memory_mib=mem, priority=prio,
            lifetime_s=round(life, 3) if life is not None else None,
            tenant=tenants[i % len(tenants)] if tenants else "",
            namespace=namespace,
        ))
    return Trace(events=events, family=family, seed=seed,
                 duration_s=duration_s)


# ---------------------------------------------------------------------------
# JSONL interchange


def write_trace_jsonl(trace: Trace, path: str) -> None:
    """One header line (family/seed/duration provenance) + one compact
    JSON document per event, arrival-ordered. Floats serialize via
    repr so the round-trip is bit-exact."""
    with open(path, "w") as f:
        f.write(json.dumps({
            "header": True, "family": trace.family, "seed": trace.seed,
            "duration_s": trace.duration_s,
            "events": len(trace.events)}, sort_keys=True) + "\n")
        for e in trace.events:
            f.write(json.dumps(e.to_dict(), sort_keys=True) + "\n")


def load_trace_jsonl(path: str) -> Trace:
    events: List[TraceEvent] = []
    family, seed, duration = "", 0, 0.0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            if d.get("header"):
                family = d.get("family", "")
                seed = int(d.get("seed", 0))
                duration = float(d.get("duration_s", 0.0))
                continue
            events.append(TraceEvent.from_dict(d))
    events.sort(key=lambda e: (e.t, e.name))
    return Trace(events=events, family=family, seed=seed,
                 duration_s=duration)


def events_to_pods(events: Iterable[TraceEvent]):
    """Materialize Pod objects for a batch of events (uids stamped from
    the event name — replay re-creations never collide)."""
    from kubernetes_tpu.api.types import Pod

    out = []
    for e in events:
        pod = Pod.from_dict(e.pod_dict())
        pod.metadata.uid = f"rp-{e.name}"
        out.append(pod)
    return out
