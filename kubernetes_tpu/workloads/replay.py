"""Open-loop trace-replay engine + quiesce invariants.

The engine drives a :class:`~kubernetes_tpu.workloads.trace.Trace`
against a cluster target (the in-process ``ClusterStore`` or a
``RestClusterClient`` — anything exposing the store surface):

- **arrival**: pods are created ON A CLOCK by the shared
  arrival-injection loop (``harness/burst.py::stream_arrivals``) —
  open-loop, nothing waits on binds, so a slow scheduler faces a
  growing backlog exactly like a production control plane;
- **lifetime churn**: a bound pod whose trace lifetime elapses is
  EXPIRED into a deletion (bulk ``delete_pods`` — the mass-delete path
  in ``scheduler/eventhandlers.py``), so capacity continuously
  recycles and the solver never sees a monotone fill;
- **latency from arrival**: the engine stamps each pod at send and
  observes its bind on its OWN watch stream — arrival→bind is the
  latency a submitting user experiences, including queue wait, solver
  batching, and watch delivery;
- **quiesce classification**: at the end every injected pod is
  accounted bound/pending/expired/preempted — anything else is LOST,
  and zero-lost is a hard invariant of every replay row and chaos
  cell.

jax-free by design (the REST harness's child processes and the chaos
matrix import this); numpy only for the quiesce invariant math.
"""

from __future__ import annotations

import heapq
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.harness.burst import (
    create_chunk,
    sample_percentile,
    stream_arrivals,
)
from kubernetes_tpu.workloads.trace import Trace, TraceEvent, events_to_pods


@dataclass
class ReplayStats:
    """The engine's postmortem (everything a replay row/cell reports)."""

    family: str
    injected: int
    expected: int            # trace size; injected < expected = faults
    ever_bound: int
    bound_at_end: int
    pending_at_end: int
    expired: int
    preempted: int
    lost: int
    offered_rate: float            # arrivals/s actually offered
    duration_s: float              # injection start → stats collection
    arrival_to_bind: Dict[str, Dict[str, float]]   # cls -> {p50,p99,...}
    gangs_total: int = 0
    gangs_placed: int = 0
    gangs_partial: int = 0         # the atomicity violation counter
    mean_gang_adjacency: Optional[float] = None
    priority_inversions: int = 0
    last_bind_s: float = 0.0       # offset of the final observed bind
    lost_names: List[str] = field(default_factory=list)
    send_errors: List[str] = field(default_factory=list)

    @property
    def gangs_intact(self) -> bool:
        return self.gangs_partial == 0

    def latency_p99_ms(self, cls: str = "all") -> float:
        return self.arrival_to_bind.get(cls, {}).get("p99", 0.0) * 1000


class ReplayEngine:
    """One replay run against one target. Lifecycle::

        eng = ReplayEngine(target, trace)
        eng.start()            # watch + injector + expirer threads
        ... caller pumps its scheduler ...
        eng.wait_injected()    # trace exhausted
        ... caller pumps to quiescence ...
        stats = eng.finish()   # stop threads, classify, compute stats

    ``time_scale`` compresses the trace clock (0 = inject everything
    immediately: the pre-created-burst degenerate case the rate=∞
    differential guard compares against). ``expire`` gates lifetime
    churn. The engine never touches the scheduler — arrival, expiry and
    observation ride the same API surface every other client uses.
    """

    def __init__(
        self,
        target,
        trace: Trace,
        *,
        time_scale: float = 1.0,
        expire: bool = True,
        chunk: int = 256,
        flush_window: float = 0.02,
        tenant_targets: Optional[Dict[str, object]] = None,
        progress: Optional[Callable[[str], None]] = None,
    ):
        self.target = target
        # per-tenant clients (the REST tenancy family: each tenant's
        # arrivals and expiries ride ITS OWN authenticated client, so
        # APF fair-queues the tenants as separate flows); unmapped
        # tenants fall back to the default target
        self.tenant_targets = tenant_targets or {}
        self.trace = trace
        self.time_scale = time_scale
        self.expire = expire
        self.chunk = chunk
        self.flush_window = flush_window
        self.progress = progress
        self._events: Dict[str, TraceEvent] = {
            e.name: e for e in trace.events}
        self._lock = threading.Lock()
        self._arrival: Dict[str, float] = {}
        self._bind: Dict[str, Tuple[float, str]] = {}   # name -> (t, node)
        self._deleted: Dict[str, str] = {}   # name -> "expired"|"other"
        self._expiry_heap: List[Tuple[float, str]] = []
        self._expired_sent: set = set()
        self._stop = threading.Event()
        self.injection_done = threading.Event()
        self._t0: Optional[float] = None
        self._watch_handle = None
        self._threads: List[threading.Thread] = []
        self._send_errors: List[str] = []

    # ------------------------------------------------------------------
    # lifecycle

    def start(self) -> None:
        self._t0 = time.monotonic()
        # observe binds/deletes on our own stream BEFORE injecting: a
        # bind landing between create and watch-attach must not vanish
        self._watch_handle = self.target.watch(
            self._on_event, batch_fn=self._on_events)
        inj = threading.Thread(target=self._inject, daemon=True,
                               name="replay-inject")
        inj.start()
        self._threads.append(inj)
        if self.expire:
            exp = threading.Thread(target=self._expirer, daemon=True,
                                   name="replay-expire")
            exp.start()
            self._threads.append(exp)

    def wait_injected(self, timeout: Optional[float] = None) -> bool:
        return self.injection_done.wait(timeout)

    def pending_expiries(self) -> int:
        with self._lock:
            return len(self._expiry_heap)

    def due_expiries(self) -> int:
        """Expiries already due (bound pods whose lifetime has elapsed
        but whose delete hasn't been sent yet) — the caller's quiesce
        condition waits for THESE, not for far-future lifetimes."""
        now = time.monotonic()
        with self._lock:
            return sum(1 for t, _ in self._expiry_heap if t <= now)

    def finish(self) -> ReplayStats:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        if self._watch_handle is not None:
            stop = getattr(self._watch_handle, "stop", None)
            if stop is not None:
                stop()
        return self._collect()

    # ------------------------------------------------------------------
    # injector / expirer threads

    def _inject(self) -> None:
        try:
            n = stream_arrivals(
                ((e.t, e) for e in self.trace.events),
                self._send_chunk,
                chunk=self.chunk,
                time_scale=self.time_scale,
                flush_window=self.flush_window,
                stop=self._stop,
                on_sent=self._note_sent,
            )
            if self.progress:
                self.progress(f"replay: {n} arrivals injected")
        except Exception as e:  # noqa: BLE001 — surfaced via stats
            self._send_errors.append(f"{type(e).__name__}: {e}")
        finally:
            self.injection_done.set()

    def _target_for(self, tenant: str):
        return self.tenant_targets.get(tenant, self.target)

    def _send_chunk(self, events: List[TraceEvent]) -> None:
        if not self.tenant_targets:
            create_chunk(self.target, events_to_pods(events))
            return
        by_tenant: Dict[str, List[TraceEvent]] = {}
        for e in events:
            by_tenant.setdefault(e.tenant, []).append(e)
        for tenant, evs in by_tenant.items():
            create_chunk(self._target_for(tenant), events_to_pods(evs))

    def _note_sent(self, event: TraceEvent, offset_s: float) -> None:
        with self._lock:
            self._arrival[event.name] = offset_s

    def _expirer(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            due: List[str] = []
            with self._lock:
                while self._expiry_heap and \
                        self._expiry_heap[0][0] <= now:
                    _, name = heapq.heappop(self._expiry_heap)
                    if name not in self._deleted:
                        due.append(name)
                        self._expired_sent.add(name)
            if due:
                by_tenant: Dict[str, List[str]] = {}
                for n in due:
                    by_tenant.setdefault(self._events[n].tenant,
                                         []).append(n)
                for tenant, names in by_tenant.items():
                    target = self._target_for(tenant)
                    for lo in range(0, len(names), self.chunk):
                        part = names[lo:lo + self.chunk]
                        try:
                            target.delete_pods(
                                [(self._events[n].namespace, n)
                                 for n in part])
                        except Exception:  # noqa: BLE001 — a pod
                            # already deleted (preempted under us) is
                            # fine; retry one-by-one so siblings still
                            # expire
                            for n in part:
                                try:
                                    target.delete_pod(
                                        self._events[n].namespace, n)
                                except Exception:  # noqa: BLE001
                                    pass
            self._stop.wait(0.05)

    # ------------------------------------------------------------------
    # watch observation

    def _on_event(self, event) -> None:
        self._on_events([event])

    def _on_events(self, events) -> None:
        now = time.monotonic()
        with self._lock:
            for e in events:
                if getattr(e, "kind", "Pod") != "Pod":
                    continue
                obj = e.obj
                name = obj.metadata.name
                ev = self._events.get(name)
                if ev is None:
                    continue
                if e.type == "DELETED":
                    if name not in self._deleted:
                        self._deleted[name] = (
                            "expired" if name in self._expired_sent
                            else "other")
                    continue
                if obj.spec.node_name and name not in self._bind:
                    self._bind[name] = (now - self._t0,
                                        obj.spec.node_name)
                    if self.expire and ev.lifetime_s is not None:
                        heapq.heappush(
                            self._expiry_heap,
                            (now + ev.lifetime_s * self.time_scale
                             if self.time_scale > 0
                             else now + ev.lifetime_s, name))

    # ------------------------------------------------------------------
    # postmortem

    def _collect(self) -> ReplayStats:
        duration = time.monotonic() - self._t0 if self._t0 else 0.0
        live: Dict[str, object] = {}
        for pod in self.target.list_pods():
            if pod.metadata.name in self._events:
                live[pod.metadata.name] = pod
        with self._lock:
            arrival = dict(self._arrival)
            bind = dict(self._bind)
            deleted = dict(self._deleted)
            expired_intent = set(self._expired_sent)
        bound_now = [n for n, p in live.items() if p.spec.node_name]
        pending_now = [n for n, p in live.items()
                       if not p.spec.node_name]
        # classification consults the engine's own delete INTENT
        # (_expired_sent) as well as the observed watch events: the
        # final DELETED events may still be in flight when finish()
        # stops the stream, and an intentionally-expired (or
        # preempted-after-bind) pod must not flip to LOST on that race
        expired_set = {
            n for n in arrival if n not in live
            and (n in expired_intent or deleted.get(n) == "expired")}
        preempted = [n for n in arrival
                     if n not in live and n not in expired_set
                     and n in bind]
        lost = [n for n in arrival
                if n not in live and n not in expired_set
                and n not in bind]
        # arrival→bind latency, per workload class + overall
        lat_by_cls: Dict[str, List[float]] = {"all": []}
        for n, (t_bind, _node) in bind.items():
            t_arr = arrival.get(n)
            if t_arr is None:
                continue
            lat = max(0.0, t_bind - t_arr)
            lat_by_cls["all"].append(lat)
            cls = self._events[n].cls
            if cls:
                lat_by_cls.setdefault(cls, []).append(lat)
        lat_summary = {
            cls: {
                "count": len(vals),
                "p50": sample_percentile(vals, 0.50),
                "p90": sample_percentile(vals, 0.90),
                "p99": sample_percentile(vals, 0.99),
                "max": max(vals) if vals else 0.0,
            }
            for cls, vals in lat_by_cls.items()
        }
        gangs = self._gang_integrity(bind)
        stats = ReplayStats(
            family=self.trace.family,
            injected=len(arrival),
            expected=len(self.trace.events),
            ever_bound=len(bind),
            bound_at_end=len(bound_now),
            pending_at_end=len(pending_now),
            expired=len(expired_set),
            preempted=len(preempted),
            lost=len(lost),
            offered_rate=(
                len(arrival) / (self.trace.duration_s * self.time_scale)
                if self.time_scale > 0 and self.trace.duration_s > 0
                else 0.0),
            duration_s=duration,
            arrival_to_bind=lat_summary,
            gangs_total=gangs[0],
            gangs_placed=gangs[1],
            gangs_partial=gangs[2],
            mean_gang_adjacency=self._adjacency(bind),
            priority_inversions=self._priority_inversions(live),
            last_bind_s=max((t for t, _ in bind.values()), default=0.0),
            lost_names=sorted(lost)[:20],
        )
        stats.send_errors = list(self._send_errors)
        return stats

    def _gang_integrity(self, bind: Dict) -> Tuple[int, int, int]:
        """(total, fully-placed, PARTIAL) over the trace's gangs —
        partial means some but not all members ever bound: the
        atomicity violation gang semantics must prevent."""
        members: Dict[str, List[str]] = {}
        size: Dict[str, int] = {}
        for e in self.trace.events:
            if e.gang and e.gang_size > 1:
                members.setdefault(e.gang, []).append(e.name)
                size[e.gang] = e.gang_size
        placed = partial = 0
        for gang, names in members.items():
            n_bound = sum(1 for n in names if n in bind)
            if n_bound == 0:
                continue
            if n_bound >= size[gang]:
                placed += 1
            else:
                partial += 1
        return len(members), placed, partial

    def _adjacency(self, bind: Dict) -> Optional[float]:
        """Mean over placed gangs of the mean pairwise Manhattan
        distance between member nodes on the device mesh; None when no
        gang landed on labeled nodes. Lower is better — the gang
        family's scored arm must beat its adjacency-blind arm here."""
        from kubernetes_tpu.scheduler.framework.plugins.mesh_locality import (  # noqa: E501
            node_coord,
        )

        coords = {}
        for node in self.target.list_nodes():
            c = node_coord(node)
            if c is not None:
                coords[node.metadata.name] = c
        if not coords:
            return None
        members: Dict[str, List[Tuple[int, int]]] = {}
        for e in self.trace.events:
            if not e.gang:
                continue
            hit = bind.get(e.name)
            if hit is None:
                continue
            c = coords.get(hit[1])
            if c is not None:
                members.setdefault(e.gang, []).append(c)
        dists = []
        for pts in members.values():
            if len(pts) < 2:
                continue
            acc = cnt = 0
            for i in range(len(pts)):
                for j in range(i + 1, len(pts)):
                    acc += (abs(pts[i][0] - pts[j][0])
                            + abs(pts[i][1] - pts[j][1]))
                    cnt += 1
            dists.append(acc / cnt)
        return (sum(dists) / len(dists)) if dists else None

    def _priority_inversions(self, live: Dict) -> int:
        """No-priority-inversion-at-quiesce check: a PENDING pod whose
        request would fit on some node after evicting only
        strictly-lower-priority pods is an inversion — preemption
        should have placed it. Gang members count only when the WHOLE
        gang could be placed that way simultaneously (a partially
        fitting gang is correctly pending, not inverted). cpu+memory
        accounting only — same granularity as the preemption screen."""
        from kubernetes_tpu.scheduler.types import (
            Resource,
            compute_pod_resource_request,
        )

        nodes = list(self.target.list_nodes())
        if not nodes:
            return 0
        name_idx = {n.metadata.name: i for i, n in enumerate(nodes)}
        alloc = np.zeros((len(nodes), 2), dtype=np.int64)
        for i, n in enumerate(nodes):
            r = Resource.from_resource_list(n.status.allocatable)
            alloc[i, 0] = r.milli_cpu
            alloc[i, 1] = r.memory
        # per-node, per-priority usage by BOUND pods
        used = np.zeros((len(nodes), 2), dtype=np.int64)
        by_prio: Dict[int, np.ndarray] = {}
        for pod in self.target.list_pods():
            node_i = name_idx.get(pod.spec.node_name or "")
            if node_i is None:
                continue
            req = compute_pod_resource_request(pod)
            row = np.array([req.milli_cpu, req.memory], dtype=np.int64)
            used[node_i] += row
            p = pod.priority()
            if p not in by_prio:
                by_prio[p] = np.zeros((len(nodes), 2), dtype=np.int64)
            by_prio[p][node_i] += row
        prios = sorted(by_prio)
        free = alloc - used

        def headroom_below(prio: int) -> np.ndarray:
            h = free.copy()
            for p in prios:
                if p < prio:
                    h += by_prio[p]
            return h

        pending = [p for p in live.values() if not p.spec.node_name]
        inversions = 0
        gangs_seen: Dict[str, List] = {}
        for pod in pending:
            ev = self._events.get(pod.metadata.name)
            if ev is not None and ev.gang and ev.gang_size > 1:
                gangs_seen.setdefault(ev.gang, []).append(pod)
                continue
            req = compute_pod_resource_request(pod)
            need = np.array([req.milli_cpu, req.memory],
                            dtype=np.int64)
            if np.any(np.all(headroom_below(pod.priority()) >= need,
                             axis=1)):
                inversions += 1
        for gang, pods in gangs_seen.items():
            size = next((self._events[p.metadata.name].gang_size
                         for p in pods), 0)
            bound_members = sum(
                1 for e in self.trace.events
                if e.gang == gang and e.name not in
                {p.metadata.name for p in pods}
                and e.name in self._bind)
            if bound_members + len(pods) < size:
                continue   # members missing entirely; not placeable
            # greedy first-fit-decreasing of the pending members into
            # lower-priority headroom: all fit → inversion
            h = headroom_below(max(p.priority() for p in pods))
            reqs = sorted(
                (compute_pod_resource_request(p) for p in pods),
                key=lambda r: -r.milli_cpu)
            ok = True
            for r in reqs:
                need = np.array([r.milli_cpu, r.memory], dtype=np.int64)
                fits = np.nonzero(np.all(h >= need, axis=1))[0]
                if fits.size == 0:
                    ok = False
                    break
                h[fits[0]] -= need
            if ok:
                inversions += len(pods)
        return inversions
