"""The hard scenario families (ISSUE 13): three trace builders that
stress the dense-tensor solver where it hurts, each judged by SLO
verdicts and hard invariants rather than raw throughput.

- ``storm`` — **preemption storm under priority inversion**: a
  low-priority flood pins ~120% of cluster capacity while
  high-priority GANGS arrive mid-storm; the solver must mass-decline,
  the preemption screen must plan victims at batch rate, and the
  mass-delete path in ``scheduler/eventhandlers.py`` absorbs the
  evictions. Invariants: zero lost pods, gang atomicity, and NO
  priority inversion at quiesce (no pending pod that could fit by
  evicting only strictly-lower-priority pods).

- ``gangs`` — **device-locality gangs**: nodes carry mesh coordinates
  (``ktpu.io/mesh-x``/``-y``), multi-chip gangs carry
  ``ktpu.io/mesh-block``, and the MeshLocality score pulls members
  onto mesh-adjacent hosts while short-lived filler churn fragments
  the grid. Members are sized so no two share a node (one chip host
  each). The bench row A/Bs the scored arm against an
  adjacency-blind arm — mean gang adjacency must be strictly better.

- ``tenancy`` — **mixed serve+batch tenancy**: a latency-sensitive
  serve tenant (small, short-lived, steady Poisson) shares the fabric
  with a throughput batch tenant (heavy-tailed sizes, bursty, long
  lifetimes), with the PR 4 autoscaler buying capacity and PR 6 APF
  fair-queuing the tenants. The row's verdict is the serve class's
  arrival→bind p99 staying within budget WHILE batch floods.

Every builder is a pure function of (seed, scale) — the determinism
contract of ``workloads/trace.py`` extends here (asserted in tier-1).
jax-free by design.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from random import Random
from typing import Callable, Dict, List, Tuple

from kubernetes_tpu.harness.workloads import node_template
from kubernetes_tpu.scheduler.framework.plugins.mesh_locality import (
    MESH_BLOCK_LABEL,
    mesh_node_labels,
)
from kubernetes_tpu.workloads.trace import (
    Trace,
    TraceEvent,
    arrivals_exactly,
    bounded_pareto,
    lognormal_mixture,
    poisson_arrivals,
)


@dataclass
class FamilySpec:
    """One scenario family: the trace builder, the node fleet it
    assumes, and which quiesce invariants its rows/cells must enforce
    (``checks`` ⊆ {lost, inversion, gangs, adjacency, serve_latency})."""

    name: str
    title: str
    build: Callable[[int, float], Trace]
    node_specs: Callable[[float], List[dict]]
    checks: Tuple[str, ...]
    tenants: Tuple[str, ...] = ()
    autoscale: bool = False
    description: str = ""
    # SLOs excluded from the row's strict verdict: a preemption storm
    # (and a capacity-acquiring tenancy trace) INTENDS some pods to
    # wait multiple seconds — schedule-latency violations there are
    # the scenario, not a regression. The excluded verdicts still ride
    # the row's ``freshness.slo`` sub-object; only the pass/fail gate
    # skips them (``slo_gated`` on the row names what WAS gated).
    slo_exempt: Tuple[str, ...] = ()
    extras: Dict = field(default_factory=dict)


def _sorted_trace(events: List[TraceEvent], family: str, seed: int,
                  duration_s: float) -> Trace:
    events.sort(key=lambda e: (e.t, e.name))
    return Trace(events=events, family=family, seed=seed,
                 duration_s=duration_s)


# ---------------------------------------------------------------------------
# storm: preemption storm under priority inversion

STORM_DURATION_S = 45.0
STORM_NODE_CPU = 4          # cores per node (the Preemption bench shape)
STORM_GANG_SIZE = 6
STORM_GANG_CPU_MILLI = 2500
STORM_FLOOD_PRIO = 1
STORM_GANG_PRIO = 100


def _storm_nodes(scale: float) -> int:
    return max(16, int(round(120 * scale)))


def storm_nodes(scale: float) -> List[dict]:
    return [node_template(i, cpu=str(STORM_NODE_CPU), memory="8Gi")
            for i in range(_storm_nodes(scale))]


def build_storm(seed: int, scale: float = 1.0) -> Trace:
    rng = Random(seed * 7919 + 1)
    n_nodes = _storm_nodes(scale)
    capacity_milli = n_nodes * STORM_NODE_CPU * 1000
    # flood sized to ~120% of capacity at its mean request: capacity
    # pins, the tail stays pending — the inversion bait
    flood_lo, flood_hi = 500, 3000
    # empirical mean of bounded-Pareto(1.6, 500, 3000) — measured at
    # 932.3 over 200k draws; an overstated mean would quietly shrink
    # the flood below the oversubscription the scenario promises
    mean_cpu = 932.0
    n_flood = max(8, int(capacity_milli * 1.2 / mean_cpu))
    # gangs sized to need ~45% of capacity back: preemption at rates no
    # pre-created row reaches
    n_gangs = max(2, int(capacity_milli * 0.45
                         / (STORM_GANG_SIZE * STORM_GANG_CPU_MILLI)))
    d = STORM_DURATION_S
    events: List[TraceEvent] = []
    flood_ts = arrivals_exactly(rng, n_flood, 0.45 * d,
                                burst_factor=3.0, burst_period_s=6.0)
    for i, t in enumerate(flood_ts):
        cpu = int(bounded_pareto(rng, 1.6, flood_lo, flood_hi))
        events.append(TraceEvent(
            t=round(t, 6), name=f"flood-{i}", cpu_milli=cpu,
            memory_mib=max(128, cpu), priority=STORM_FLOOD_PRIO,
            # mid-length lifetimes: enough churn that the scheduler
            # never sees a static fill, long enough that capacity
            # stays pinned when the gangs arrive
            lifetime_s=round(lognormal_mixture(
                rng, ((0.7, math.log(18.0), 0.5),
                      (0.3, math.log(60.0), 0.4))), 3),
            cls="flood",
        ))
    for g in range(n_gangs):
        t_g = 0.42 * d + (0.48 * d) * g / max(n_gangs - 1, 1)
        for m in range(STORM_GANG_SIZE):
            events.append(TraceEvent(
                t=round(t_g + rng.uniform(0.0, 0.25), 6),
                name=f"hp-gang-{g}-{m}",
                cpu_milli=STORM_GANG_CPU_MILLI,
                memory_mib=2048, priority=STORM_GANG_PRIO,
                lifetime_s=None,    # the preemptors keep what they take
                cls="gang", gang=f"hp-gang-{g}",
                gang_size=STORM_GANG_SIZE,
            ))
    return _sorted_trace(events, "storm", seed, d)


# ---------------------------------------------------------------------------
# gangs: device-locality gangs on the mesh grid

GANGS_DURATION_S = 30.0
GANGS_NODE_CPU = 8
GANG_SIZE = 4
GANG_MEMBER_CPU_MILLI = 4500    # > half a node: one chip host each


def mesh_grid(scale: float) -> Tuple[int, int]:
    side = max(4, int(round(8 * math.sqrt(scale))))
    return side, side


def gangs_nodes(scale: float) -> List[dict]:
    cols, rows = mesh_grid(scale)
    out = []
    for i in range(cols * rows):
        d = node_template(i, cpu=str(GANGS_NODE_CPU), memory="16Gi")
        d["metadata"]["labels"].update(mesh_node_labels(i, cols, rows))
        out.append(d)
    return out


def build_gangs(seed: int, scale: float = 1.0) -> Trace:
    rng = Random(seed * 104729 + 2)
    cols, rows = mesh_grid(scale)
    n_nodes = cols * rows
    n_gangs = max(3, n_nodes // 5)
    d = GANGS_DURATION_S
    events: List[TraceEvent] = []
    # background filler: short-lived fragmentation pressure arriving
    # the whole run (so gang placement happens against churn, not a
    # pristine grid)
    fill_rate = max(4.0, n_nodes / 3.0)
    for i, t in enumerate(poisson_arrivals(rng, fill_rate, d,
                                           burst_factor=2.0,
                                           burst_period_s=5.0)):
        cpu = int(bounded_pareto(rng, 1.8, 200, 900))
        events.append(TraceEvent(
            t=round(t, 6), name=f"fill-{i}", cpu_milli=cpu,
            memory_mib=max(128, cpu),
            lifetime_s=round(rng.uniform(3.0, 9.0), 3),
            cls="filler",
        ))
    # the gangs: multi-chip pods that must land mesh-adjacent; members
    # carry the mesh-block label (anchor = crc32(block) on the grid)
    for g in range(n_gangs):
        t_g = 0.08 * d + (0.8 * d) * g / max(n_gangs - 1, 1)
        block = f"mc-gang-{g}"
        for m in range(GANG_SIZE):
            events.append(TraceEvent(
                t=round(t_g + rng.uniform(0.0, 0.2), 6),
                name=f"mc-gang-{g}-{m}",
                cpu_milli=GANG_MEMBER_CPU_MILLI, memory_mib=4096,
                priority=10,
                lifetime_s=round(rng.uniform(12.0, 20.0), 3),
                cls="gang", gang=block, gang_size=GANG_SIZE,
                labels={MESH_BLOCK_LABEL: block,
                        "ktpu.io/chips": "4"},
            ))
    return _sorted_trace(events, "gangs", seed, d)


# ---------------------------------------------------------------------------
# tenancy: mixed serve+batch tenants (autoscaler + APF active)

TENANCY_DURATION_S = 45.0
TENANCY_NODE_CPU = 8
SERVE_TENANT, BATCH_TENANT = "tenant-serve", "tenant-batch"


def _tenancy_sizing(scale: float) -> Tuple[int, int, int]:
    """(serve pods, batch pods, initial nodes). Initial capacity is
    ~45% of what the combined steady state needs — the autoscaler buys
    the rest while both tenants stream."""
    n_serve = max(30, int(round(500 * scale)))
    n_batch = max(20, int(round(380 * scale)))
    # steady-state demand estimate: serve ~250m × short overlap, batch
    # heavy-tailed mean ~1200m × long overlap
    demand_milli = int(n_serve * 250 * 0.25 + n_batch * 1200 * 0.7)
    need = max(4, math.ceil(demand_milli / (TENANCY_NODE_CPU * 1000)))
    return n_serve, n_batch, max(3, int(math.ceil(0.45 * need)))


def tenancy_nodes(scale: float) -> List[dict]:
    _, _, initial = _tenancy_sizing(scale)
    return [node_template(i, cpu=str(TENANCY_NODE_CPU), memory="32Gi")
            for i in range(initial)]


def build_tenancy(seed: int, scale: float = 1.0) -> Trace:
    rng = Random(seed * 65537 + 3)
    n_serve, n_batch, _ = _tenancy_sizing(scale)
    d = TENANCY_DURATION_S
    events: List[TraceEvent] = []
    # serve: latency-sensitive, small, short-lived, steady Poisson
    serve_ts = arrivals_exactly(rng, n_serve, d)
    for i, t in enumerate(serve_ts):
        cpu = int(rng.uniform(100, 400))
        events.append(TraceEvent(
            t=round(t, 6), name=f"serve-{i}", cpu_milli=cpu,
            memory_mib=max(128, cpu), priority=50,
            lifetime_s=round(rng.uniform(6.0, 14.0), 3),
            tenant=SERVE_TENANT, cls="serve",
        ))
    # batch: throughput tenant — heavy-tailed sizes, bursty arrivals,
    # long lifetimes (they hold what they take)
    batch_ts = arrivals_exactly(rng, n_batch, 0.85 * d,
                                burst_factor=4.0, burst_period_s=8.0)
    for i, t in enumerate(batch_ts):
        cpu = int(bounded_pareto(rng, 1.5, 400, 4000))
        events.append(TraceEvent(
            t=round(t, 6), name=f"batch-{i}", cpu_milli=cpu,
            memory_mib=max(256, cpu), priority=0,
            lifetime_s=round(lognormal_mixture(
                rng, ((0.6, math.log(25.0), 0.5),
                      (0.4, math.log(70.0), 0.4))), 3),
            tenant=BATCH_TENANT, cls="batch",
        ))
    return _sorted_trace(events, "tenancy", seed, d)


# ---------------------------------------------------------------------------
# registry

REPLAY_FAMILIES: Dict[str, FamilySpec] = {
    "storm": FamilySpec(
        name="storm",
        title="preemption storm under priority inversion",
        build=build_storm,
        node_specs=storm_nodes,
        checks=("lost", "inversion", "gangs"),
        slo_exempt=("schedule_latency",),
        description="low-priority flood pins capacity; high-priority "
                    "gangs preempt their way in mid-storm",
    ),
    "gangs": FamilySpec(
        name="gangs",
        title="device-locality gangs on the mesh grid",
        build=build_gangs,
        node_specs=gangs_nodes,
        checks=("lost", "gangs", "adjacency"),
        description="multi-chip gangs must land mesh-adjacent against "
                    "filler churn; scored vs adjacency-blind A/B",
        extras={"grid": mesh_grid},
    ),
    "tenancy": FamilySpec(
        name="tenancy",
        title="mixed serve+batch tenancy (autoscaler + APF)",
        build=build_tenancy,
        node_specs=tenancy_nodes,
        checks=("lost", "serve_latency"),
        tenants=(SERVE_TENANT, BATCH_TENANT),
        autoscale=True,
        slo_exempt=("schedule_latency",),
        description="latency-sensitive serve pods vs heavy-tailed "
                    "batch pods from separate tenants",
    ),
}


def build_family(family: str, seed: int, scale: float = 1.0) -> Trace:
    spec = REPLAY_FAMILIES[family]
    return spec.build(seed, scale)
