"""Object model + apimachinery subset.

The reference spreads this over ``staging/src/k8s.io/apimachinery`` (56.7k
LoC) and ``staging/src/k8s.io/api`` (280k generated LoC); the scheduler only
needs a focused slice: resource quantities, label selectors, object meta, and
the Pod/Node families of types. See SURVEY.md section 2.6.
"""

from kubernetes_tpu.api.resource import Quantity, parse_quantity
from kubernetes_tpu.api.labels import (
    LabelSelector,
    Requirement,
    Selector,
    parse_selector,
)
from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodCondition,
    PodSpec,
    PodStatus,
    PreferredSchedulingTerm,
    ResourceRequirements,
    Service,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    Volume,
    WeightedPodAffinityTerm,
)
