"""Resource quantities.

Behavioral equivalent of the reference's ``apimachinery/pkg/api/resource``
Quantity (suffix grammar: decimal SI ``n u m "" k M G T P E``, binary
``Ki Mi Gi Ti Pi Ei``, and scientific notation), reduced to what scheduling
needs: parse, compare, add/sub, and the two canonical scalar views the
scheduler's Resource vectors use (``milli_value`` for cpu,
``value`` for memory/storage/counts).

Unlike the reference (infinite-precision inf.Dec), we store an exact
integer count of nano-units. Nano is the finest suffix the grammar admits,
so every parseable quantity is exact; scheduling math in the reference
happens on int64 MilliCPU/bytes anyway (``pkg/scheduler/framework/types.go``
Resource), which this representation round-trips losslessly.
"""

from __future__ import annotations

import math
import re
from functools import total_ordering

_NANO = 10**9

_SUFFIXES = {
    "n": 1,                      # nano
    "u": 10**3,                  # micro
    "m": 10**6,                  # milli
    "": _NANO,
    "k": _NANO * 10**3,
    "M": _NANO * 10**6,
    "G": _NANO * 10**9,
    "T": _NANO * 10**12,
    "P": _NANO * 10**15,
    "E": _NANO * 10**18,
    "Ki": _NANO * 2**10,
    "Mi": _NANO * 2**20,
    "Gi": _NANO * 2**30,
    "Ti": _NANO * 2**40,
    "Pi": _NANO * 2**50,
    "Ei": _NANO * 2**60,
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:(?P<suffix>[numkMGTPE]i?|Ki)|[eE](?P<exp>[+-]?\d+))?$"
)


@total_ordering
class Quantity:
    """An exact resource amount, stored as integer nano-units."""

    __slots__ = ("nano",)

    def __init__(self, nano: int = 0):
        object.__setattr__(self, "nano", int(nano))

    def __setattr__(self, name, value):
        # instances are shared via the parse cache; in-place mutation
        # would silently change every holder of the same request string
        raise AttributeError("Quantity is immutable")

    def __reduce__(self):
        # immutability blocks pickle's default __setstate__ path; the
        # binary wire codec (apiserver/codec.py) pickles whole objects
        return (Quantity, (self.nano,))

    # --- constructors -------------------------------------------------
    @classmethod
    def from_milli(cls, milli: int) -> "Quantity":
        return cls(int(milli) * 10**6)

    @classmethod
    def from_value(cls, value: int) -> "Quantity":
        return cls(int(value) * _NANO)

    # --- views --------------------------------------------------------
    def milli_value(self) -> int:
        """Ceiling milli-units (reference Quantity.MilliValue rounds up)."""
        return -((-self.nano) // 10**6)

    def value(self) -> int:
        """Ceiling whole units (reference Quantity.Value rounds up)."""
        return -((-self.nano) // _NANO)

    # --- arithmetic ---------------------------------------------------
    def __add__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.nano + other.nano)

    def __sub__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.nano - other.nano)

    def __eq__(self, other) -> bool:
        return isinstance(other, Quantity) and self.nano == other.nano

    def __lt__(self, other: "Quantity") -> bool:
        return self.nano < other.nano

    def __hash__(self):
        return hash(self.nano)

    def __bool__(self):
        return self.nano != 0

    def __repr__(self):
        return f"Quantity({self.to_string()!r})"

    def to_string(self) -> str:
        """Canonical-ish rendering: prefer whole units, then m, then n."""
        if self.nano % _NANO == 0:
            return str(self.nano // _NANO)
        if self.nano % 10**6 == 0:
            return f"{self.nano // 10**6}m"
        return f"{self.nano}n"


_PARSE_CACHE: dict = {}
_PARSE_CACHE_MAX = 4096


def parse_quantity(s) -> Quantity:
    """Parse a quantity string (or int/float unit count) into a Quantity.

    Accepts the reference grammar's common forms: "100m", "2", "1.5",
    "64Mi", "2Gi", "1e3", "500". Raises ValueError on garbage.

    String parses are memoized (bounded): workloads repeat a handful of
    request strings across tens of thousands of pods, and Quantity is
    immutable after construction, so sharing instances is safe.
    """
    if type(s) is str:
        q = _PARSE_CACHE.get(s)
        if q is None:
            q = _parse_quantity_uncached(s)
            if len(_PARSE_CACHE) < _PARSE_CACHE_MAX:
                _PARSE_CACHE[s] = q
        return q
    return _parse_quantity_uncached(s)


def _parse_quantity_uncached(s) -> Quantity:
    if isinstance(s, Quantity):
        return s
    if isinstance(s, bool):
        raise ValueError(f"cannot parse quantity from bool {s!r}")
    if isinstance(s, int):
        return Quantity.from_value(s)
    if isinstance(s, float):
        if not math.isfinite(s):
            raise ValueError(f"cannot parse quantity from {s!r}")
        return Quantity(round(s * _NANO))
    m = _QUANTITY_RE.match(s.strip())
    if not m:
        raise ValueError(f"invalid quantity {s!r}")
    sign = -1 if m.group("sign") == "-" else 1
    num = m.group("num")
    if m.group("exp") is not None:
        scale = _NANO
        exp = int(m.group("exp"))
    else:
        suffix = m.group("suffix") or ""
        if suffix not in _SUFFIXES:
            raise ValueError(f"invalid quantity suffix in {s!r}")
        scale = _SUFFIXES[suffix]
        exp = 0
    # exact decimal -> integer nano computation
    if "." in num:
        int_part, frac_part = num.split(".")
        int_part = int_part or "0"
        digits = int(int_part + frac_part)
        denom = 10 ** len(frac_part)
    else:
        digits = int(num)
        denom = 1
    if exp >= 0:
        numer = digits * scale * 10**exp
    else:
        denom *= 10**(-exp)
        numer = digits * scale
    if numer % denom != 0:
        # sub-nano precision: round half away from zero like inf.Dec scaling
        nano = (numer + denom // 2) // denom
    else:
        nano = numer // denom
    return Quantity(sign * nano)
