"""Label selector matching.

Behavioral subset of the reference's ``apimachinery/pkg/labels`` (Selector,
Requirement) and ``metav1.LabelSelector`` conversion, which the scheduler
uses for inter-pod affinity terms, topology-spread constraints, and service
selector spreading. Operators: In, NotIn, Exists, DoesNotExist, plus the
node-field operators Gt, Lt (integer comparison).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Mapping, Optional, Sequence

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

_VALID_OPS = {IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT}


@dataclass(frozen=True)
class Requirement:
    key: str
    operator: str
    values: tuple = ()

    def __post_init__(self):
        if self.operator not in _VALID_OPS:
            raise ValueError(f"invalid selector operator {self.operator!r}")
        object.__setattr__(self, "values", tuple(self.values))

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.operator == EXISTS:
            return has
        if self.operator == DOES_NOT_EXIST:
            return not has
        if not has:
            return False
        v = labels[self.key]
        if self.operator == IN:
            return v in self.values
        if self.operator == NOT_IN:
            return v not in self.values
        # Gt / Lt: both sides must parse as integers
        try:
            lhs = int(v)
            rhs = int(self.values[0])
        except (ValueError, IndexError):
            return False
        return lhs > rhs if self.operator == GT else lhs < rhs


@dataclass(frozen=True)
class Selector:
    """Conjunction of requirements. Empty selector matches everything;
    use ``Selector.nothing()`` for the never-matching selector (the
    reference's invalid-selector conversion result)."""

    requirements: tuple = ()
    _nothing: bool = False

    def __post_init__(self):
        object.__setattr__(self, "requirements", tuple(self.requirements))

    @classmethod
    def everything(cls) -> "Selector":
        return cls(())

    @classmethod
    def nothing(cls) -> "Selector":
        return cls((), _nothing=True)

    @classmethod
    def from_map(cls, m: Optional[Mapping[str, str]]) -> "Selector":
        if not m:
            return cls.everything()
        return cls(tuple(Requirement(k, IN, (v,)) for k, v in sorted(m.items())))

    def matches(self, labels: Optional[Mapping[str, str]]) -> bool:
        if self._nothing:
            return False
        labels = labels or {}
        return all(r.matches(labels) for r in self.requirements)

    def is_empty(self) -> bool:
        return not self._nothing and not self.requirements


@dataclass
class LabelSelector:
    """metav1.LabelSelector: matchLabels AND matchExpressions."""

    match_labels: dict = field(default_factory=dict)
    match_expressions: list = field(default_factory=list)  # list[Requirement]

    def to_selector(self) -> Selector:
        """Reference LabelSelectorAsSelector: nil selector matches nothing,
        empty selector matches everything."""
        reqs = [Requirement(k, IN, (v,)) for k, v in sorted(self.match_labels.items())]
        for e in self.match_expressions:
            if isinstance(e, Requirement):
                reqs.append(e)
            else:  # dict form {key, operator, values}
                reqs.append(
                    Requirement(e["key"], e["operator"], tuple(e.get("values") or ()))
                )
        return Selector(tuple(reqs))

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> Optional["LabelSelector"]:
        if d is None:
            return None
        return cls(
            match_labels=dict(d.get("matchLabels") or {}),
            match_expressions=[
                Requirement(e["key"], e["operator"], tuple(e.get("values") or ()))
                for e in (d.get("matchExpressions") or [])
            ],
        )


def selector_from_label_selector(ls: Optional[LabelSelector]) -> Selector:
    """nil → match-nothing (reference labels.Nothing())."""
    if ls is None:
        return Selector.nothing()
    return ls.to_selector()


def parse_selector(s: str) -> Selector:
    """Parse a simple string selector: "a=b,c!=d,e in (f,g),h,!i".

    Covers the subset of the reference's labels.Parse grammar that in-tree
    components actually emit.
    """
    s = s.strip()
    if not s:
        return Selector.everything()
    reqs = []
    for part in _split_top_level(s):
        part = part.strip()
        if part.startswith("!"):
            reqs.append(Requirement(part[1:].strip(), DOES_NOT_EXIST))
        elif " notin " in part:
            key, vals = part.split(" notin ", 1)
            reqs.append(Requirement(key.strip(), NOT_IN, _parse_values(vals)))
        elif " in " in part:
            key, vals = part.split(" in ", 1)
            reqs.append(Requirement(key.strip(), IN, _parse_values(vals)))
        elif "!=" in part:
            key, val = part.split("!=", 1)
            reqs.append(Requirement(key.strip(), NOT_IN, (val.strip(),)))
        elif "==" in part:
            key, val = part.split("==", 1)
            reqs.append(Requirement(key.strip(), IN, (val.strip(),)))
        elif "=" in part:
            key, val = part.split("=", 1)
            reqs.append(Requirement(key.strip(), IN, (val.strip(),)))
        else:
            reqs.append(Requirement(part, EXISTS))
    return Selector(tuple(reqs))


def _parse_values(vals: str) -> tuple:
    vals = vals.strip()
    if not (vals.startswith("(") and vals.endswith(")")):
        raise ValueError(f"expected parenthesized value list, got {vals!r}")
    return tuple(v.strip() for v in vals[1:-1].split(",") if v.strip())


def _split_top_level(s: str) -> Iterable[str]:
    """Split on commas not inside parentheses."""
    depth, start, out = 0, 0, []
    for i, ch in enumerate(s):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(s[start:i])
            start = i + 1
    out.append(s[start:])
    return out
