"""Versioned API machinery: the runtime.Scheme analog (reference
``staging/src/k8s.io/apimachinery/pkg/runtime/scheme.go`` + the
generated per-version conversion/defaulting in ``pkg/apis/<group>``).

The reference's model is hub-and-spoke: every group has an INTERNAL
(hub) type; each served VERSION registers defaulting (applied on
decode, before conversion) and a pair of conversion functions
(versioned wire ↔ internal). This module carries the same model over
the wire-dict representation: the internal hub is the typed dataclass
scheme (``api/serialization.py``), spokes are wire-shape transforms.

Registered spokes:

- ``autoscaling/v1`` HorizontalPodAutoscaler — flat
  ``targetCpuUtilizationPercentage`` (the internal hub shape),
- ``autoscaling/v2`` HorizontalPodAutoscaler — the ``metrics`` list
  with Resource/Utilization targets, converted losslessly to/from the
  hub for the cpu-utilization metric the controller consumes,
- ``batch/v1beta1`` CronJob — the reference's nested
  ``spec.jobTemplate.spec`` wire shape (``pkg/apis/batch/v1beta1``)
  against the flat internal hub, with v1beta1 defaulting
  (``defaults.go``: concurrencyPolicy/suspend/history limits),
- ``policy/v1beta1`` PodDisruptionBudget — nested
  ``spec.{selector,minAvailable,maxUnavailable}``
  (``pkg/apis/policy/v1beta1``) against the flat hub.

A versioned field with NO internal representation raises
``UnconvertibleError`` (the reference's conversion functions return
errors; the codec surfaces them as 400s) — version skew must fail
loudly, not silently drop data.

New versions register at runtime (``SCHEME_V.register_version``) — the
same extension point the reference's scheme builders use.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from kubernetes_tpu.api.serialization import from_wire, to_wire

INTERNAL_VERSION = "v1"  # the hub (legacy core routes serve it directly)


class UnconvertibleError(ValueError):
    """A versioned field has no internal representation — conversion
    must reject rather than silently drop it."""

Defaulter = Callable[[Dict[str, Any]], None]
Converter = Callable[[Dict[str, Any]], Dict[str, Any]]


class VersionedScheme:
    """Registry of (apiVersion, kind) spokes around the internal hub."""

    def __init__(self):
        # (api_version, kind) -> (defaulter, to_internal, from_internal)
        self._spokes: Dict[
            Tuple[str, str],
            Tuple[Optional[Defaulter], Converter, Converter],
        ] = {}

    def register_version(
        self,
        api_version: str,
        kind: str,
        to_internal: Converter,
        from_internal: Converter,
        defaulter: Optional[Defaulter] = None,
    ) -> None:
        self._spokes[(api_version, kind)] = (
            defaulter, to_internal, from_internal,
        )

    def kinds_for(self, api_version: str):
        return [k for (v, k) in self._spokes if v == api_version]

    def recognizes(self, api_version: str, kind: str) -> bool:
        return api_version == INTERNAL_VERSION or \
            (api_version, kind) in self._spokes

    # -- decode/encode --------------------------------------------------
    def decode(self, body: Dict[str, Any], kind: str,
               api_version: str) -> Any:
        """Versioned wire dict → internal typed object: defaulting
        (versioned), then conversion to the hub, then the typed decode
        (reference codec DecodeToVersion → default → convert)."""
        if api_version != INTERNAL_VERSION:
            spoke = self._spokes.get((api_version, kind))
            if spoke is None:
                raise TypeError(
                    f"no kind {kind!r} registered in {api_version!r}"
                )
            defaulter, to_internal, _ = spoke
            if defaulter is not None:
                import copy

                # defaulters mutate nested dicts (spec): never leak the
                # injected fields into the CALLER's request body
                body = copy.deepcopy(body)
                defaulter(body)
            body = to_internal(body)
        return from_wire(body, kind)

    def encode(self, obj: Any, api_version: str) -> Dict[str, Any]:
        """Internal typed object → versioned wire dict."""
        d = to_wire(obj)
        if api_version == INTERNAL_VERSION:
            return d
        kind = d.get("kind", "")
        spoke = self._spokes.get((api_version, kind))
        if spoke is None:
            raise TypeError(
                f"no kind {kind!r} registered in {api_version!r}"
            )
        _, _, from_internal = spoke
        out = from_internal(d)
        out["apiVersion"] = api_version
        out["kind"] = kind
        return out


# ---------------------------------------------------------------------------
# autoscaling/v2 spoke for HorizontalPodAutoscaler


def _hpa_v2_defaults(d: Dict[str, Any]) -> None:
    """v2 defaulting (reference pkg/apis/autoscaling/v2/defaults.go):
    minReplicas defaults to 1; an absent metrics list defaults to 80%
    cpu utilization."""
    spec = d.setdefault("spec", {}) if "spec" in d else d
    if spec.get("minReplicas") is None:
        spec["minReplicas"] = 1
    if not spec.get("metrics"):
        spec["metrics"] = [{
            "type": "Resource",
            "resource": {
                "name": "cpu",
                "target": {"type": "Utilization",
                           "averageUtilization": 80},
            },
        }]


def _hpa_v2_to_internal(d: Dict[str, Any]) -> Dict[str, Any]:
    """v2 → hub (reference pkg/apis/autoscaling/v2/conversion.go):
    the cpu Resource/Utilization metric folds back into the flat
    targetCpuUtilizationPercentage field."""
    out = {k: v for k, v in d.items()
           if k not in ("metrics", "spec", "apiVersion")}
    src = d.get("spec", d)
    for key in ("scaleTargetRef", "minReplicas", "maxReplicas"):
        if key in src:
            out[key] = src[key]
    for m in src.get("metrics") or []:
        res = m.get("resource") or {}
        target = res.get("target") or {}
        if (
            m.get("type") == "Resource" and res.get("name") == "cpu"
            and target.get("type") == "Utilization"
        ):
            out["targetCpuUtilizationPercentage"] = \
                target.get("averageUtilization", 80)
            break
    return out


def _hpa_v2_from_internal(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: v for k, v in d.items() if k not in (
        "scaleTargetRef", "minReplicas", "maxReplicas",
        "targetCpuUtilizationPercentage", "apiVersion", "kind",
    )}
    out["spec"] = {
        "scaleTargetRef": d.get("scaleTargetRef") or {},
        "minReplicas": d.get("minReplicas", 1),
        "maxReplicas": d.get("maxReplicas", 1),
        "metrics": [{
            "type": "Resource",
            "resource": {
                "name": "cpu",
                "target": {
                    "type": "Utilization",
                    "averageUtilization": d.get(
                        "targetCpuUtilizationPercentage", 80),
                },
            },
        }],
    }
    return out


def _hpa_v1_identity(d: Dict[str, Any]) -> Dict[str, Any]:
    # autoscaling/v1 IS the hub shape; conversion is a relabel
    return {k: v for k, v in d.items() if k != "apiVersion"}


# ---------------------------------------------------------------------------
# batch/v1beta1 spoke for CronJob (reference pkg/apis/batch/v1beta1:
# nested spec.jobTemplate.spec wire shape vs the flat internal hub)

_CJ_META = ("metadata", "kind", "apiVersion")


def _cronjob_v1beta1_defaults(d: Dict[str, Any]) -> None:
    """v1beta1 defaulting (pkg/apis/batch/v1beta1/defaults.go
    SetDefaults_CronJob): concurrencyPolicy Allow, suspend false,
    successfulJobsHistoryLimit 3, failedJobsHistoryLimit 1."""
    spec = d.setdefault("spec", {})
    if not spec.get("concurrencyPolicy"):
        spec["concurrencyPolicy"] = "Allow"
    if spec.get("suspend") is None:
        spec["suspend"] = False
    if spec.get("successfulJobsHistoryLimit") is None:
        spec["successfulJobsHistoryLimit"] = 3
    if spec.get("failedJobsHistoryLimit") is None:
        spec["failedJobsHistoryLimit"] = 1


def _reject_unknown(spec: Dict[str, Any], allowed: tuple,
                    where: str) -> None:
    """Conversion must fail loudly on fields with no hub
    representation — a 201 that silently drops data is version skew's
    worst failure mode."""
    unknown = sorted(set(spec) - set(allowed))
    if unknown:
        raise UnconvertibleError(
            f"{where} field(s) {', '.join(unknown)} have no internal "
            f"representation"
        )


_CJ_SPEC_FIELDS = ("schedule", "suspend", "concurrencyPolicy",
                   "startingDeadlineSeconds",
                   "successfulJobsHistoryLimit",
                   "failedJobsHistoryLimit", "jobTemplate")
_CJ_JT_FIELDS = ("completions", "parallelism",
                 "ttlSecondsAfterFinished", "template")


def _cronjob_v1beta1_to_internal(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: v for k, v in d.items() if k in _CJ_META and
           k != "apiVersion"}
    out["kind"] = "CronJob"
    spec = d.get("spec") or {}
    _reject_unknown(spec, _CJ_SPEC_FIELDS, "batch/v1beta1 CronJob spec")
    _reject_unknown((spec.get("jobTemplate") or {}).get("spec") or {},
                    _CJ_JT_FIELDS,
                    "batch/v1beta1 CronJob spec.jobTemplate.spec")
    for src, dst in (("schedule", "schedule"), ("suspend", "suspend"),
                     ("concurrencyPolicy", "concurrencyPolicy"),
                     ("startingDeadlineSeconds",
                      "startingDeadlineSeconds")):
        if src in spec:
            out[dst] = spec[src]
    # the hub carries no history-limit fields: the v1beta1 DEFAULTS are
    # representable (they're implied), any OTHER value is data the hub
    # would silently lose — reject it (weak #5's unconvertible path)
    if spec.get("successfulJobsHistoryLimit") not in (None, 3):
        raise UnconvertibleError(
            "successfulJobsHistoryLimit has no internal representation "
            "(only the v1beta1 default 3 round-trips)"
        )
    if spec.get("failedJobsHistoryLimit") not in (None, 1):
        raise UnconvertibleError(
            "failedJobsHistoryLimit has no internal representation "
            "(only the v1beta1 default 1 round-trips)"
        )
    jt = spec.get("jobTemplate") or {}
    jt_spec = jt.get("spec") or {}
    for src, dst in (("completions", "completions"),
                     ("parallelism", "parallelism"),
                     ("ttlSecondsAfterFinished",
                      "ttlSecondsAfterFinished")):
        if src in jt_spec:
            out[dst] = jt_spec[src]
    if "template" in jt_spec:
        out["jobTemplate"] = jt_spec["template"]
    status = d.get("status") or {}
    if "lastScheduleTime" in status:
        out["lastScheduleTime"] = status["lastScheduleTime"]
    return out


def _cronjob_v1beta1_from_internal(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: v for k, v in d.items() if k in _CJ_META}
    jt_spec: Dict[str, Any] = {}
    for src, dst in (("completions", "completions"),
                     ("parallelism", "parallelism"),
                     ("ttlSecondsAfterFinished",
                      "ttlSecondsAfterFinished")):
        if src in d:
            jt_spec[dst] = d[src]
    if "jobTemplate" in d:
        jt_spec["template"] = d["jobTemplate"]
    spec: Dict[str, Any] = {
        "jobTemplate": {"spec": jt_spec},
        "successfulJobsHistoryLimit": 3,
        "failedJobsHistoryLimit": 1,
    }
    for key in ("schedule", "suspend", "concurrencyPolicy",
                "startingDeadlineSeconds"):
        if key in d:
            spec[key] = d[key]
    out["spec"] = spec
    if "lastScheduleTime" in d:
        out["status"] = {"lastScheduleTime": d["lastScheduleTime"]}
    return out


# ---------------------------------------------------------------------------
# policy/v1beta1 spoke for PodDisruptionBudget (reference
# pkg/apis/policy/v1beta1: nested spec.{selector,minAvailable,
# maxUnavailable} vs the flat hub)


def _pdb_v1beta1_to_internal(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: v for k, v in d.items()
           if k not in ("spec", "apiVersion")}
    spec = d.get("spec") or {}
    _reject_unknown(spec, ("minAvailable", "maxUnavailable", "selector"),
                    "policy/v1beta1 PodDisruptionBudget spec")
    if "minAvailable" in spec:
        out["minAvailable"] = spec["minAvailable"]
    if "maxUnavailable" in spec:
        out["maxUnavailable"] = spec["maxUnavailable"]
    if "selector" in spec:
        out["labelSelector"] = spec["selector"]
    return out


def _pdb_v1beta1_from_internal(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: v for k, v in d.items() if k not in (
        "minAvailable", "maxUnavailable", "labelSelector",
        "apiVersion", "kind",
    )}
    spec: Dict[str, Any] = {}
    if "minAvailable" in d:
        spec["minAvailable"] = d["minAvailable"]
    if "maxUnavailable" in d:
        spec["maxUnavailable"] = d["maxUnavailable"]
    if "labelSelector" in d:
        spec["selector"] = d["labelSelector"]
    out["spec"] = spec
    return out


SCHEME_V = VersionedScheme()
SCHEME_V.register_version(
    "autoscaling/v1", "HorizontalPodAutoscaler",
    to_internal=_hpa_v1_identity,
    from_internal=lambda d: dict(d),
)
SCHEME_V.register_version(
    "autoscaling/v2", "HorizontalPodAutoscaler",
    to_internal=_hpa_v2_to_internal,
    from_internal=_hpa_v2_from_internal,
    defaulter=_hpa_v2_defaults,
)
SCHEME_V.register_version(
    "batch/v1beta1", "CronJob",
    to_internal=_cronjob_v1beta1_to_internal,
    from_internal=_cronjob_v1beta1_from_internal,
    defaulter=_cronjob_v1beta1_defaults,
)
SCHEME_V.register_version(
    "policy/v1beta1", "PodDisruptionBudget",
    to_internal=_pdb_v1beta1_to_internal,
    from_internal=_pdb_v1beta1_from_internal,
)
