"""Versioned API machinery: the runtime.Scheme analog (reference
``staging/src/k8s.io/apimachinery/pkg/runtime/scheme.go`` + the
generated per-version conversion/defaulting in ``pkg/apis/<group>``).

The reference's model is hub-and-spoke: every group has an INTERNAL
(hub) type; each served VERSION registers defaulting (applied on
decode, before conversion) and a pair of conversion functions
(versioned wire ↔ internal). This module carries the same model over
the wire-dict representation: the internal hub is the typed dataclass
scheme (``api/serialization.py``), spokes are wire-shape transforms.

Registered spokes (the demonstration group, mirroring upstream's most
visibly version-split API):

- ``autoscaling/v1`` HorizontalPodAutoscaler — flat
  ``targetCpuUtilizationPercentage`` (the internal hub shape),
- ``autoscaling/v2`` HorizontalPodAutoscaler — the ``metrics`` list
  with Resource/Utilization targets, converted losslessly to/from the
  hub for the cpu-utilization metric the controller consumes.

New versions register at runtime (``SCHEME_V.register_version``) — the
same extension point the reference's scheme builders use.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from kubernetes_tpu.api.serialization import from_wire, to_wire

INTERNAL_VERSION = "v1"  # the hub (legacy core routes serve it directly)

Defaulter = Callable[[Dict[str, Any]], None]
Converter = Callable[[Dict[str, Any]], Dict[str, Any]]


class VersionedScheme:
    """Registry of (apiVersion, kind) spokes around the internal hub."""

    def __init__(self):
        # (api_version, kind) -> (defaulter, to_internal, from_internal)
        self._spokes: Dict[
            Tuple[str, str],
            Tuple[Optional[Defaulter], Converter, Converter],
        ] = {}

    def register_version(
        self,
        api_version: str,
        kind: str,
        to_internal: Converter,
        from_internal: Converter,
        defaulter: Optional[Defaulter] = None,
    ) -> None:
        self._spokes[(api_version, kind)] = (
            defaulter, to_internal, from_internal,
        )

    def kinds_for(self, api_version: str):
        return [k for (v, k) in self._spokes if v == api_version]

    def recognizes(self, api_version: str, kind: str) -> bool:
        return api_version == INTERNAL_VERSION or \
            (api_version, kind) in self._spokes

    # -- decode/encode --------------------------------------------------
    def decode(self, body: Dict[str, Any], kind: str,
               api_version: str) -> Any:
        """Versioned wire dict → internal typed object: defaulting
        (versioned), then conversion to the hub, then the typed decode
        (reference codec DecodeToVersion → default → convert)."""
        if api_version != INTERNAL_VERSION:
            spoke = self._spokes.get((api_version, kind))
            if spoke is None:
                raise TypeError(
                    f"no kind {kind!r} registered in {api_version!r}"
                )
            defaulter, to_internal, _ = spoke
            if defaulter is not None:
                import copy

                # defaulters mutate nested dicts (spec): never leak the
                # injected fields into the CALLER's request body
                body = copy.deepcopy(body)
                defaulter(body)
            body = to_internal(body)
        return from_wire(body, kind)

    def encode(self, obj: Any, api_version: str) -> Dict[str, Any]:
        """Internal typed object → versioned wire dict."""
        d = to_wire(obj)
        if api_version == INTERNAL_VERSION:
            return d
        kind = d.get("kind", "")
        spoke = self._spokes.get((api_version, kind))
        if spoke is None:
            raise TypeError(
                f"no kind {kind!r} registered in {api_version!r}"
            )
        _, _, from_internal = spoke
        out = from_internal(d)
        out["apiVersion"] = api_version
        out["kind"] = kind
        return out


# ---------------------------------------------------------------------------
# autoscaling/v2 spoke for HorizontalPodAutoscaler


def _hpa_v2_defaults(d: Dict[str, Any]) -> None:
    """v2 defaulting (reference pkg/apis/autoscaling/v2/defaults.go):
    minReplicas defaults to 1; an absent metrics list defaults to 80%
    cpu utilization."""
    spec = d.setdefault("spec", {}) if "spec" in d else d
    if spec.get("minReplicas") is None:
        spec["minReplicas"] = 1
    if not spec.get("metrics"):
        spec["metrics"] = [{
            "type": "Resource",
            "resource": {
                "name": "cpu",
                "target": {"type": "Utilization",
                           "averageUtilization": 80},
            },
        }]


def _hpa_v2_to_internal(d: Dict[str, Any]) -> Dict[str, Any]:
    """v2 → hub (reference pkg/apis/autoscaling/v2/conversion.go):
    the cpu Resource/Utilization metric folds back into the flat
    targetCpuUtilizationPercentage field."""
    out = {k: v for k, v in d.items()
           if k not in ("metrics", "spec", "apiVersion")}
    src = d.get("spec", d)
    for key in ("scaleTargetRef", "minReplicas", "maxReplicas"):
        if key in src:
            out[key] = src[key]
    for m in src.get("metrics") or []:
        res = m.get("resource") or {}
        target = res.get("target") or {}
        if (
            m.get("type") == "Resource" and res.get("name") == "cpu"
            and target.get("type") == "Utilization"
        ):
            out["targetCpuUtilizationPercentage"] = \
                target.get("averageUtilization", 80)
            break
    return out


def _hpa_v2_from_internal(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: v for k, v in d.items() if k not in (
        "scaleTargetRef", "minReplicas", "maxReplicas",
        "targetCpuUtilizationPercentage", "apiVersion", "kind",
    )}
    out["spec"] = {
        "scaleTargetRef": d.get("scaleTargetRef") or {},
        "minReplicas": d.get("minReplicas", 1),
        "maxReplicas": d.get("maxReplicas", 1),
        "metrics": [{
            "type": "Resource",
            "resource": {
                "name": "cpu",
                "target": {
                    "type": "Utilization",
                    "averageUtilization": d.get(
                        "targetCpuUtilizationPercentage", 80),
                },
            },
        }],
    }
    return out


def _hpa_v1_identity(d: Dict[str, Any]) -> Dict[str, Any]:
    # autoscaling/v1 IS the hub shape; conversion is a relabel
    return {k: v for k, v in d.items() if k != "apiVersion"}


SCHEME_V = VersionedScheme()
SCHEME_V.register_version(
    "autoscaling/v1", "HorizontalPodAutoscaler",
    to_internal=_hpa_v1_identity,
    from_internal=lambda d: dict(d),
)
SCHEME_V.register_version(
    "autoscaling/v2", "HorizontalPodAutoscaler",
    to_internal=_hpa_v2_to_internal,
    from_internal=_hpa_v2_from_internal,
    defaulter=_hpa_v2_defaults,
)
