"""Wire codec: typed API objects ↔ JSON-able dicts.

The behavioral equivalent of the reference's apimachinery runtime.Scheme +
Codec stack (``staging/src/k8s.io/apimachinery/pkg/runtime/scheme.go``,
``serializer/json``): a kind registry plus a generic, reflection-driven
encoder/decoder over the dataclass API types, with Kubernetes wire
conventions (camelCase keys, quantity strings, ``kind`` discriminator).
This is what crosses the HTTP process boundary between the REST server
(``kubernetes_tpu.apiserver.rest``) and remote clients — the same boundary
the reference crosses with protobuf/JSON between kube-apiserver and
client-go.

Encoding rules:
- dataclass field names snake_case → camelCase
- ``Quantity`` → canonical string (whole units, milli, or nano suffix)
- empty containers / default-equal scalars are elided (compact wire form)
- every top-level object carries ``{"kind": ..., "apiVersion": "v1"}``
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, Optional, get_args, get_origin, get_type_hints

from kubernetes_tpu.api import types as api_types
from kubernetes_tpu.api.labels import LabelSelector, Requirement
from kubernetes_tpu.api.resource import _NANO, Quantity

# ---------------------------------------------------------------------------
# Scheme: the kind registry (reference runtime.Scheme.AddKnownTypes)

SCHEME: Dict[str, type] = {
    name: getattr(api_types, name)
    for name in (
        "Pod",
        "Node",
        "Service",
        "Endpoints",
        "ReplicaSet",
        "ReplicationController",
        "StatefulSet",
        "Deployment",
        "DaemonSet",
        "Job",
        "PersistentVolumeClaim",
        "PersistentVolume",
        "StorageClass",
        "CSINode",
        "PodDisruptionBudget",
        "Event",
        "Namespace",
        "ResourceQuota",
        "ServiceAccount",
        "CronJob",
        "HorizontalPodAutoscaler",
        "EndpointSlice",
        "Role",
        "ClusterRole",
        "RoleBinding",
        "ClusterRoleBinding",
        "CustomResourceDefinition",
        "MutatingWebhookConfiguration",
        "ValidatingWebhookConfiguration",
        "Secret",
        "ConfigMap",
        "CertificateSigningRequest",
        "PriorityClass",
        "Lease",
    )
}


# schema metadata: which kinds are namespace-scoped (clients need this to
# build paths; it is API schema, not storage layout)
CLUSTER_SCOPED = {"Node", "PersistentVolume", "StorageClass", "CSINode",
                  "PriorityClass",
                  "Namespace", "ClusterRole", "ClusterRoleBinding",
                  "CustomResourceDefinition",
                  "MutatingWebhookConfiguration",
                  "ValidatingWebhookConfiguration",
                  "CertificateSigningRequest"}


def is_namespaced(kind: str) -> bool:
    return kind not in CLUSTER_SCOPED


def kind_of(obj: Any) -> str:
    k = type(obj).__name__
    if k not in SCHEME:
        raise TypeError(f"unregistered kind {k!r}")
    return k


def _camel(s: str) -> str:
    head, *rest = s.split("_")
    return head + "".join(w.capitalize() for w in rest)


def quantity_to_string(q: Quantity) -> str:
    n = q.nano
    if n % _NANO == 0:
        return str(n // _NANO)
    if n % 10**6 == 0:
        return f"{n // 10**6}m"
    return f"{n}n"


def _encode(value: Any) -> Any:
    if isinstance(value, Quantity):
        return quantity_to_string(value)
    if isinstance(value, Requirement):
        return {
            "key": value.key,
            "operator": value.operator,
            "values": list(value.values),
        }
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        out = {}
        for f in dataclasses.fields(value):
            v = getattr(value, f.name)
            if v is None:
                continue
            if isinstance(v, (dict, list, tuple)) and not v:
                continue
            out[_camel(f.name)] = _encode(v)
        return out
    if isinstance(value, dict):
        return {k: _encode(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    return value


def to_wire(obj: Any) -> Dict[str, Any]:
    """Encode a typed object for the wire, with kind discriminator.
    CustomObject instances (runtime-registered kinds) carry their OWN
    kind string — the dynamic-client unstructured path."""
    from kubernetes_tpu.api.types import CustomObject

    d = _encode(obj)
    if isinstance(obj, CustomObject):
        d.pop("kind", None)
        d["kind"] = obj.kind
    else:
        d["kind"] = kind_of(obj)
    d["apiVersion"] = "v1"
    return d


# ---------------------------------------------------------------------------
# Decoding: reflection over dataclass type hints

_hints_cache: Dict[type, Dict[str, Any]] = {}


def _hints(cls: type) -> Dict[str, Any]:
    h = _hints_cache.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _hints_cache[cls] = h
    return h


def _decode(hint: Any, value: Any) -> Any:
    if value is None:
        return None
    origin = get_origin(hint)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in get_args(hint) if a is not type(None)]
        return _decode(args[0], value) if args else value
    if hint is Quantity:
        from kubernetes_tpu.api.resource import parse_quantity

        return parse_quantity(value)
    if hint is Requirement:
        return Requirement(
            value["key"], value["operator"], tuple(value.get("values") or ())
        )
    if dataclasses.is_dataclass(hint):
        hints = _hints(hint)
        kwargs = {}
        for f in dataclasses.fields(hint):
            wire_key = _camel(f.name)
            if wire_key in value:
                kwargs[f.name] = _decode(hints[f.name], value[wire_key])
        return hint(**kwargs)
    if origin in (dict, typing.Dict):
        kh, vh = (get_args(hint) + (Any, Any))[:2]
        return {k: _decode(vh, v) for k, v in value.items()}
    if origin in (list, typing.List):
        (eh,) = get_args(hint) or (Any,)
        return [_decode(eh, v) for v in value]
    if origin in (tuple, typing.Tuple):
        args = get_args(hint)
        eh = args[0] if args else Any
        return tuple(_decode(eh, v) for v in value)
    return value


def from_wire(d: Dict[str, Any], kind: Optional[str] = None) -> Any:
    """Decode a wire dict into its typed object (kind from the payload's
    discriminator unless given explicitly). Kinds outside the typed
    scheme decode to CustomObject — the REST layer only routes plurals
    it knows (typed or CRD-registered), so an unknown kind here IS a
    runtime-registered one (apiextensions custom resource)."""
    k = kind or d.get("kind")
    if not k:
        raise TypeError("cannot decode object with no kind")
    cls = SCHEME.get(k)
    body = {key: v for key, v in d.items() if key not in ("kind", "apiVersion")}
    if cls is None:
        from kubernetes_tpu.api.types import CustomObject, ObjectMeta

        return CustomObject(
            kind=k,
            metadata=_decode(ObjectMeta, body.get("metadata") or {}),
            spec=body.get("spec") or {},
            status=body.get("status") or {},
        )
    return _decode(cls, body)


def roundtrip_equal(obj: Any) -> bool:
    """Debug helper: does obj survive encode→decode→encode?"""
    w = to_wire(obj)
    return to_wire(from_wire(w)) == w
