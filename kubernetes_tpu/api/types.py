"""Typed object model (the scheduler-relevant slice of core/v1 + apps/v1).

Equivalent surface to the reference's generated API types
(``staging/src/k8s.io/api/core/v1/types.go``), hand-written as plain Python
dataclasses with ``from_dict`` constructors accepting k8s-manifest-shaped
dicts, so harness workload configs can be written in familiar YAML/JSON.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

from kubernetes_tpu.api.labels import LabelSelector, Requirement
from kubernetes_tpu.api.resource import Quantity, parse_quantity

# Well-known resource names (reference v1.ResourceName constants).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"
DEFAULT_MILLI_CPU_REQUEST = 100       # reference util defaults for
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024  # NonZero requests (schedutil)

# Taint effects.
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

# Well-known node taint keys (reference v1 node lifecycle taints).
TAINT_NODE_UNREACHABLE = "node.kubernetes.io/unreachable"
TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"

# Pod phases.
PENDING, RUNNING, SUCCEEDED, FAILED = "Pending", "Running", "Succeeded", "Failed"

_uid_counter = itertools.count(1)

_EMPTY: Mapping = {}  # shared empty mapping for absent-key fast paths


def shallow_copy(obj):
    """Fast shallow copy for API dataclasses. ``copy.copy`` routes
    through ``__reduce_ex__``/``_reconstruct`` (~8µs per object), which
    dominates the bind hot path at thousands of pods/sec; a ``__dict__``
    copy is semantically identical for plain (non-slots) dataclasses."""
    new = object.__new__(type(obj))
    new.__dict__.update(obj.__dict__)
    return new


def new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: str = ""
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    owner_references: List[dict] = field(default_factory=list)
    finalizers: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping) -> "ObjectMeta":
        # hot path (one per admitted object): direct construction, no
        # dataclass kwarg processing
        m = object.__new__(cls)
        g = d.get
        m.name = g("name", "")
        m.namespace = g("namespace", "default")
        m.uid = g("uid") or new_uid()
        m.labels = dict(g("labels") or ())
        m.annotations = dict(g("annotations") or ())
        m.resource_version = ""
        m.creation_timestamp = 0.0
        m.deletion_timestamp = None
        m.owner_references = list(g("ownerReferences") or ())
        m.finalizers = list(g("finalizers") or ())
        return m


def _parse_resource_list(d: Optional[Mapping]) -> Dict[str, Quantity]:
    return {k: parse_quantity(v) for k, v in (d or {}).items()}


@dataclass
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""

    @classmethod
    def from_dict(cls, d: Mapping) -> "ContainerPort":
        return cls(
            container_port=int(d.get("containerPort") or 0),
            host_port=int(d.get("hostPort") or 0),
            protocol=d.get("protocol") or "TCP",
            host_ip=d.get("hostIP") or "",
        )


@dataclass
class ResourceRequirements:
    requests: Dict[str, Quantity] = field(default_factory=dict)
    limits: Dict[str, Quantity] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "ResourceRequirements":
        r = object.__new__(cls)
        if d:
            req = d.get("requests")
            lim = d.get("limits")
            r.requests = _parse_resource_list(req) if req else {}
            r.limits = _parse_resource_list(lim) if lim else {}
        else:
            r.requests = {}
            r.limits = {}
        return r


@dataclass
class Container:
    name: str = ""
    image: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    ports: List[ContainerPort] = field(default_factory=list)
    # "" = cluster default (IfNotPresent/Always by tag); the
    # AlwaysPullImages admission plugin forces "Always"
    image_pull_policy: str = ""
    # core/v1 Lifecycle: {"postStart": {...}, "preStop": {...}} hook
    # payloads, opaque to the control plane (the runtime executes them;
    # the kubelet sequences them around start/termination)
    lifecycle: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "Container":
        c = object.__new__(cls)
        g = d.get
        c.name = g("name", "")
        c.image = g("image", "")
        c.resources = ResourceRequirements.from_dict(g("resources"))
        ports = g("ports")
        c.ports = [ContainerPort.from_dict(p) for p in ports] if ports else []
        c.image_pull_policy = g("imagePullPolicy", "")
        c.lifecycle = g("lifecycle")
        return c


@dataclass
class NodeSelectorRequirement:
    key: str
    operator: str
    values: List[str] = field(default_factory=list)

    def to_requirement(self) -> Requirement:
        return Requirement(self.key, self.operator, tuple(self.values))

    @classmethod
    def from_dict(cls, d: Mapping) -> "NodeSelectorRequirement":
        return cls(d["key"], d["operator"], list(d.get("values") or []))


@dataclass
class NodeSelectorTerm:
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)
    match_fields: List[NodeSelectorRequirement] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping) -> "NodeSelectorTerm":
        return cls(
            match_expressions=[
                NodeSelectorRequirement.from_dict(e)
                for e in (d.get("matchExpressions") or [])
            ],
            match_fields=[
                NodeSelectorRequirement.from_dict(e)
                for e in (d.get("matchFields") or [])
            ],
        )


@dataclass
class NodeSelector:
    """ORed terms; each term's expressions/fields are ANDed."""

    node_selector_terms: List[NodeSelectorTerm] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> Optional["NodeSelector"]:
        if d is None:
            return None
        return cls(
            node_selector_terms=[
                NodeSelectorTerm.from_dict(t)
                for t in (d.get("nodeSelectorTerms") or [])
            ]
        )


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm

    @classmethod
    def from_dict(cls, d: Mapping) -> "PreferredSchedulingTerm":
        return cls(int(d["weight"]), NodeSelectorTerm.from_dict(d.get("preference") or {}))


@dataclass
class NodeAffinity:
    required_during_scheduling_ignored_during_execution: Optional[NodeSelector] = None
    preferred_during_scheduling_ignored_during_execution: List[
        PreferredSchedulingTerm
    ] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> Optional["NodeAffinity"]:
        if d is None:
            return None
        return cls(
            required_during_scheduling_ignored_during_execution=NodeSelector.from_dict(
                d.get("requiredDuringSchedulingIgnoredDuringExecution")
            ),
            preferred_during_scheduling_ignored_during_execution=[
                PreferredSchedulingTerm.from_dict(t)
                for t in (d.get("preferredDuringSchedulingIgnoredDuringExecution") or [])
            ],
        )


@dataclass
class PodAffinityTerm:
    label_selector: Optional[LabelSelector] = None
    namespaces: List[str] = field(default_factory=list)
    topology_key: str = ""

    @classmethod
    def from_dict(cls, d: Mapping) -> "PodAffinityTerm":
        return cls(
            label_selector=LabelSelector.from_dict(d.get("labelSelector")),
            namespaces=list(d.get("namespaces") or []),
            topology_key=d.get("topologyKey", ""),
        )


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    pod_affinity_term: PodAffinityTerm

    @classmethod
    def from_dict(cls, d: Mapping) -> "WeightedPodAffinityTerm":
        return cls(int(d["weight"]), PodAffinityTerm.from_dict(d.get("podAffinityTerm") or {}))


@dataclass
class PodAffinity:
    required_during_scheduling_ignored_during_execution: List[PodAffinityTerm] = field(
        default_factory=list
    )
    preferred_during_scheduling_ignored_during_execution: List[
        WeightedPodAffinityTerm
    ] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> Optional["PodAffinity"]:
        if d is None:
            return None
        return cls(
            required_during_scheduling_ignored_during_execution=[
                PodAffinityTerm.from_dict(t)
                for t in (d.get("requiredDuringSchedulingIgnoredDuringExecution") or [])
            ],
            preferred_during_scheduling_ignored_during_execution=[
                WeightedPodAffinityTerm.from_dict(t)
                for t in (d.get("preferredDuringSchedulingIgnoredDuringExecution") or [])
            ],
        )


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAffinity] = None

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> Optional["Affinity"]:
        if d is None:
            return None
        return cls(
            node_affinity=NodeAffinity.from_dict(d.get("nodeAffinity")),
            pod_affinity=PodAffinity.from_dict(d.get("podAffinity")),
            pod_anti_affinity=PodAffinity.from_dict(d.get("podAntiAffinity")),
        )


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" matches all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: "Taint") -> bool:
        """Reference v1helper.TolerationsTolerateTaint semantics."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        # operator Equal; empty key + Exists handled above. Empty key with
        # Equal matches only empty taint key (covered by key check).
        return self.value == taint.value

    @classmethod
    def from_dict(cls, d: Mapping) -> "Toleration":
        return cls(
            key=d.get("key", ""),
            operator=d.get("operator", "Equal"),
            value=d.get("value", ""),
            effect=d.get("effect", ""),
            toleration_seconds=d.get("tolerationSeconds"),
        )


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = NO_SCHEDULE

    @classmethod
    def from_dict(cls, d: Mapping) -> "Taint":
        return cls(d.get("key", ""), d.get("value", ""), d.get("effect", NO_SCHEDULE))


@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    label_selector: Optional[LabelSelector] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "TopologySpreadConstraint":
        return cls(
            max_skew=int(d.get("maxSkew", 1)),
            topology_key=d.get("topologyKey", ""),
            when_unsatisfiable=d.get("whenUnsatisfiable", "DoNotSchedule"),
            label_selector=LabelSelector.from_dict(d.get("labelSelector")),
        )


@dataclass
class Volume:
    name: str = ""
    # Exactly one source is typically set; we keep the ones scheduling cares about.
    persistent_volume_claim: Optional[str] = None  # claimName
    host_path: Optional[str] = None
    ephemeral: bool = False
    gce_persistent_disk: Optional[str] = None  # pdName
    aws_elastic_block_store: Optional[str] = None  # volumeID
    azure_disk: Optional[str] = None  # diskName
    rbd: Optional[dict] = None
    iscsi: Optional[dict] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "Volume":
        pvc = d.get("persistentVolumeClaim") or {}
        gce = d.get("gcePersistentDisk") or {}
        aws = d.get("awsElasticBlockStore") or {}
        azure = d.get("azureDisk") or {}
        return cls(
            name=d.get("name", ""),
            persistent_volume_claim=pvc.get("claimName"),
            host_path=(d.get("hostPath") or {}).get("path"),
            ephemeral=bool(d.get("ephemeral")),
            gce_persistent_disk=gce.get("pdName"),
            aws_elastic_block_store=aws.get("volumeID"),
            azure_disk=azure.get("diskName"),
            rbd=d.get("rbd"),
            iscsi=d.get("iscsi"),
        )


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Dict[str, Quantity] = field(default_factory=dict)
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    scheduler_name: str = "default-scheduler"
    priority: Optional[int] = None
    priority_class_name: str = ""
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    topology_spread_constraints: List[TopologySpreadConstraint] = field(
        default_factory=list
    )
    volumes: List[Volume] = field(default_factory=list)
    host_network: bool = False
    restart_policy: str = "Always"  # Always | OnFailure | Never
    # identity the pod runs as; the ServiceAccount admission plugin
    # injects "default" when unset (core/v1 spec.serviceAccountName)
    service_account_name: str = ""
    # None = the cluster default (30s, core/v1); 0 = immediate kill
    termination_grace_period_seconds: Optional[float] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "PodSpec":
        # hot path (one per admitted pod): direct construction with
        # absent-key fast paths instead of dataclass kwarg processing
        s = object.__new__(cls)
        g = d.get
        containers = g("containers")
        s.containers = (
            [Container.from_dict(c) for c in containers] if containers else []
        )
        ic = g("initContainers")
        s.init_containers = (
            [Container.from_dict(c) for c in ic] if ic else []
        )
        ov = g("overhead")
        s.overhead = _parse_resource_list(ov) if ov else {}
        s.node_name = g("nodeName", "")
        ns = g("nodeSelector")
        s.node_selector = dict(ns) if ns else {}
        aff = g("affinity")
        s.affinity = Affinity.from_dict(aff) if aff else None
        tol = g("tolerations")
        s.tolerations = (
            [Toleration.from_dict(t) for t in tol] if tol else []
        )
        s.scheduler_name = g("schedulerName") or "default-scheduler"
        s.priority = g("priority")
        s.priority_class_name = g("priorityClassName", "")
        s.preemption_policy = g("preemptionPolicy") or "PreemptLowerPriority"
        tsc = g("topologySpreadConstraints")
        s.topology_spread_constraints = (
            [TopologySpreadConstraint.from_dict(t) for t in tsc] if tsc else []
        )
        vols = g("volumes")
        s.volumes = [Volume.from_dict(v) for v in vols] if vols else []
        s.host_network = bool(g("hostNetwork"))
        s.restart_policy = g("restartPolicy") or "Always"
        s.service_account_name = g("serviceAccountName", "")
        tg = g("terminationGracePeriodSeconds")
        s.termination_grace_period_seconds = (
            float(tg) if tg is not None else None
        )
        return s


@dataclass
class PodCondition:
    type: str
    status: str
    reason: str = ""
    message: str = ""


@dataclass
class PodStatus:
    phase: str = PENDING
    conditions: List[PodCondition] = field(default_factory=list)
    nominated_node_name: str = ""
    pod_ip: str = ""
    host_ip: str = ""
    start_time: float = 0.0
    # terminal-phase record (core/v1 PodStatus.Reason/Message; e.g.
    # reason=Evicted from the kubelet's eviction manager)
    reason: str = ""
    message: str = ""

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "PodStatus":
        st = object.__new__(cls)
        st.phase = d.get("phase", PENDING) if d else PENDING
        st.conditions = []
        st.nominated_node_name = d.get("nominatedNodeName", "") if d else ""
        st.pod_ip = ""
        st.host_ip = ""
        st.start_time = 0.0
        st.reason = d.get("reason", "") if d else ""
        st.message = d.get("message", "") if d else ""
        return st


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def priority(self) -> int:
        """Reference podutil.GetPodPriority: nil priority means 0."""
        return self.spec.priority if self.spec.priority is not None else 0

    def full_name(self) -> str:
        return f"{self.namespace}/{self.name}"

    @classmethod
    def from_dict(cls, d: Mapping) -> "Pod":
        p = object.__new__(cls)
        p.metadata = ObjectMeta.from_dict(d.get("metadata") or _EMPTY)
        p.spec = PodSpec.from_dict(d.get("spec") or _EMPTY)
        p.status = PodStatus.from_dict(d.get("status"))
        return p


@dataclass
class ContainerImage:
    names: List[str] = field(default_factory=list)
    size_bytes: int = 0


@dataclass
class NodeStatus:
    capacity: Dict[str, Quantity] = field(default_factory=dict)
    allocatable: Dict[str, Quantity] = field(default_factory=dict)
    images: List[ContainerImage] = field(default_factory=list)
    conditions: List[PodCondition] = field(default_factory=list)
    # maintained by the attachdetach controller / kubelet volume manager
    volumes_attached: List[str] = field(default_factory=list)  # PV names
    volumes_in_use: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "NodeStatus":
        d = d or {}
        capacity = _parse_resource_list(d.get("capacity"))
        allocatable = _parse_resource_list(d.get("allocatable")) or dict(capacity)
        return cls(
            capacity=capacity,
            allocatable=allocatable,
            images=[
                ContainerImage(list(i.get("names") or []), int(i.get("sizeBytes") or 0))
                for i in (d.get("images") or [])
            ],
        )


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)
    provider_id: str = ""
    pod_cidr: str = ""  # allocated by the nodeipam controller

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "NodeSpec":
        d = d or {}
        return cls(
            unschedulable=bool(d.get("unschedulable")),
            taints=[Taint.from_dict(t) for t in (d.get("taints") or [])],
            provider_id=d.get("providerID", ""),
            pod_cidr=d.get("podCIDR", ""),
        )


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @classmethod
    def from_dict(cls, d: Mapping) -> "Node":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=NodeSpec.from_dict(d.get("spec")),
            status=NodeStatus.from_dict(d.get("status")),
        )


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    storage_class_name: Optional[str] = None
    access_modes: List[str] = field(default_factory=list)
    requests: Dict[str, Quantity] = field(default_factory=dict)
    volume_name: str = ""
    phase: str = "Pending"  # Pending | Bound | Lost

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    capacity: Dict[str, Quantity] = field(default_factory=dict)
    access_modes: List[str] = field(default_factory=list)
    storage_class_name: str = ""
    node_affinity: Optional[NodeSelector] = None
    claim_ref: Optional[str] = None  # "namespace/name" of bound PVC
    phase: str = "Available"
    csi_driver: str = ""  # CSI driver name when CSI-provisioned

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    volume_binding_mode: str = "Immediate"  # or WaitForFirstConsumer

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: int = 0
    protocol: str = "TCP"


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""
    session_affinity: str = "None"  # None | ClientIP

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class EndpointAddress:
    ip: str = ""
    node_name: str = ""
    target_pod: str = ""  # "ns/name" of the backing pod


@dataclass
class Endpoints:
    """Service backend addresses (reference core/v1 Endpoints, maintained
    by the endpoints controller and consumed by kube-proxy)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    addresses: List[EndpointAddress] = field(default_factory=list)
    ports: List[ServicePort] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class WorkloadStatus:
    """Common observed state for workload controllers (the slice of
    ReplicaSetStatus/DeploymentStatus/... the control loops maintain)."""

    replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0
    succeeded: int = 0  # Job only
    failed: int = 0     # Job only
    completion_time: Optional[float] = None  # Job only (ttlafterfinished)


@dataclass
class ReplicaSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    replicas: int = 0
    template: Optional[dict] = None  # manifest-shaped pod template
    status: WorkloadStatus = field(default_factory=WorkloadStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class ReplicationController:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Dict[str, str] = field(default_factory=dict)
    replicas: int = 0
    template: Optional[dict] = None
    status: WorkloadStatus = field(default_factory=WorkloadStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class StatefulSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    replicas: int = 0
    template: Optional[dict] = None
    status: WorkloadStatus = field(default_factory=WorkloadStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class Deployment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    replicas: int = 0
    template: Optional[dict] = None
    status: WorkloadStatus = field(default_factory=WorkloadStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    template: Optional[dict] = None
    status: WorkloadStatus = field(default_factory=WorkloadStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    completions: int = 1
    parallelism: int = 1
    template: Optional[dict] = None
    ttl_seconds_after_finished: Optional[int] = None
    status: WorkloadStatus = field(default_factory=WorkloadStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class HorizontalPodAutoscaler:
    """autoscaling/v1 HorizontalPodAutoscaler: the horizontalpodautoscaler
    controller scales ``scale_target_ref`` (Deployment/ReplicaSet/RC)
    toward ``target_cpu_utilization_percentage``."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    scale_target_ref: Dict[str, str] = field(default_factory=dict)  # kind/name
    min_replicas: int = 1
    max_replicas: int = 1
    target_cpu_utilization_percentage: int = 80
    # status
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization_percentage: Optional[int] = None
    last_scale_time: Optional[float] = None

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class EndpointSlice:
    """discovery/v1beta1 EndpointSlice: the endpointslice controller
    mirrors Endpoints into bounded slices (max 100 endpoints each)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    address_type: str = "IPv4"
    endpoints: List["EndpointAddress"] = field(default_factory=list)
    ports: List["ServicePort"] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class Namespace:
    """core/v1 Namespace: lifecycle phase drives the namespace
    controller's content deletion (``pkg/controller/namespace``)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    phase: str = "Active"  # Active | Terminating

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class ResourceQuota:
    """core/v1 ResourceQuota: spec.hard caps aggregate resource creation
    in a namespace; status.used is maintained by the resourcequota
    controller (``pkg/controller/resourcequota``) and consulted by the
    quota admission plugin."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    hard: Dict[str, Quantity] = field(default_factory=dict)
    used: Dict[str, Quantity] = field(default_factory=dict)  # status

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class ServiceAccount:
    """core/v1 ServiceAccount (``pkg/controller/serviceaccount`` ensures
    a "default" account exists per namespace)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    secrets: List[str] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class CronJob:
    """batch/v1beta1 CronJob (``pkg/controller/cronjob``): creates Jobs
    on a 5-field cron schedule."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    schedule: str = "* * * * *"
    job_template: Optional[dict] = None  # manifest-shaped pod template
    suspend: bool = False
    completions: int = 1
    parallelism: int = 1
    ttl_seconds_after_finished: Optional[int] = None
    # a missed fire older than this is skipped entirely (reference
    # spec.startingDeadlineSeconds, cronjob/utils.go
    # getRecentUnmetScheduleTimes earliestTime clamp)
    starting_deadline_seconds: Optional[float] = None
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    last_schedule_time: Optional[float] = None  # status

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class PodDisruptionBudgetStatus:
    """policy/v1beta1 PodDisruptionBudgetStatus: maintained by the
    disruption controller (reference ``pkg/controller/disruption/``),
    consumed LIVE by preemption's PDB-violation split."""

    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0


@dataclass
class PodDisruptionBudget:
    """policy/v1beta1 PodDisruptionBudget. Spec carries exactly one of
    ``min_available`` / ``max_unavailable`` (int count or "N%" string);
    ``status.disruptions_allowed`` is what eviction/preemption consults
    — the SPEC alone says nothing about how many disruptions are safe
    right now."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    label_selector: Optional[LabelSelector] = None
    min_available: Optional[object] = None     # int or "N%"
    max_unavailable: Optional[object] = None   # int or "N%"
    status: PodDisruptionBudgetStatus = field(
        default_factory=PodDisruptionBudgetStatus
    )

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def disruptions_allowed(self) -> int:
        return self.status.disruptions_allowed

    @property
    def selector(self):
        from kubernetes_tpu.api.labels import Selector

        if self.label_selector is None:
            return Selector.nothing()
        return self.label_selector.to_selector()


@dataclass
class CSINodeDriver:
    name: str
    node_id: str = ""
    allocatable_count: Optional[int] = None


@dataclass
class CSINode:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    drivers: List[CSINodeDriver] = field(default_factory=list)


@dataclass
class ObjectReference:
    """Reference to another API object (core/v1 ObjectReference — the
    Event's involvedObject)."""

    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""


def object_reference(obj) -> "ObjectReference":
    return ObjectReference(
        kind=type(obj).__name__,
        namespace=getattr(obj.metadata, "namespace", ""),
        name=obj.metadata.name,
        uid=obj.metadata.uid,
    )


@dataclass
class Event:
    """Kubernetes Event (core/v1 Event): the operator's primary debugging
    surface. The reference scheduler records FailedScheduling on every
    schedule failure (pkg/scheduler/scheduler.go:331 via
    recordSchedulingFailure), Scheduled on every bind, and Preempted on
    every eviction (pkg/scheduler/framework/plugins/defaultpreemption/
    default_preemption.go:698). Correlated occurrences aggregate into one
    object with a bumped ``count`` (client-side, like client-go
    tools/record)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    reason: str = ""
    message: str = ""
    type: str = "Normal"           # Normal | Warning
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
    source_component: str = ""


# ---------------------------------------------------------------------------
# RBAC API group (reference staging/src/k8s.io/api/rbac/v1/types.go;
# served by pkg/registry/rbac/, evaluated by
# plugin/pkg/auth/authorizer/rbac/rbac.go)


@dataclass
class PolicyRule:
    """One grant: the cross-product of verbs x resources (with optional
    per-object resourceNames). "*" wildcards both axes (reference
    rbac/v1 PolicyRule + VerbMatches/ResourceMatches,
    plugin/pkg/auth/authorizer/rbac/rbac.go RuleAllows)."""

    verbs: List[str] = field(default_factory=list)
    resources: List[str] = field(default_factory=list)
    resource_names: List[str] = field(default_factory=list)
    non_resource_urls: List[str] = field(default_factory=list)


@dataclass
class RBACSubject:
    """Who a binding grants to (rbac/v1 Subject)."""

    kind: str = "User"  # User | Group | ServiceAccount
    name: str = ""
    namespace: str = ""  # ServiceAccount subjects only


@dataclass
class RoleRef:
    kind: str = "ClusterRole"  # ClusterRole | Role
    name: str = ""


@dataclass
class Role:
    """Namespaced rule set (rbac/v1 Role)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    rules: List[PolicyRule] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class ClusterRole:
    """Cluster-scoped rule set (rbac/v1 ClusterRole). A role carrying
    ``aggregation_label_selectors`` is managed by the
    clusterrole-aggregation controller: its rules are the union of all
    ClusterRoles matching any selector (rbac/v1 AggregationRule)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    rules: List[PolicyRule] = field(default_factory=list)
    # each entry is a matchLabels dict (the common AggregationRule shape)
    aggregation_label_selectors: List[Dict[str, str]] = field(
        default_factory=list
    )

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class RoleBinding:
    """Grants a Role (or a ClusterRole, scoped down to this binding's
    namespace) to subjects (rbac/v1 RoleBinding)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: List[RBACSubject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class ClusterRoleBinding:
    """Grants a ClusterRole cluster-wide (rbac/v1 ClusterRoleBinding)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: List[RBACSubject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)

    @property
    def name(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------------------
# CustomResourceDefinition analog (reference
# staging/src/k8s.io/apiextensions-apiserver/pkg/apis/apiextensions/
# types.go): runtime-registered custom kinds — creating a CRD object
# registers a new plural route + storage table + watch support with NO
# edit to this module's typed kinds.


@dataclass
class PriorityClass:
    """scheduling.k8s.io/v1 PriorityClass (``pkg/apis/scheduling``):
    maps ``pod.spec.priorityClassName`` to the numeric priority
    preemption orders by. The Priority admission plugin resolves these
    from the store (``plugin/pkg/admission/priority``); one class may
    be the cluster's global default for pods naming none."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    description: str = ""
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease: the observability shape of the
    store's internal lease table (leader election + node heartbeats) —
    ``kubectl get leases`` parity."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: float = 0.0
    renew_time: float = 0.0

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class CRDNames:
    """apiextensions CustomResourceDefinitionNames (plural + kind are
    the two the routing/storage layers need)."""

    plural: str = ""
    kind: str = ""


@dataclass
class CRDVersion:
    """apiextensions CustomResourceDefinitionVersion
    (``apiextensions/types.go:23-28``): one served/storage version of a
    custom kind. Conversion strategy is None (the reference default):
    every served version carries the same payload with its own
    apiVersion stamp."""

    name: str = ""
    served: bool = True
    storage: bool = False


@dataclass
class CustomResourceDefinition:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    group: str = ""
    names: CRDNames = field(default_factory=CRDNames)
    # per-CRD version list (multi-version serving with None-conversion);
    # empty = the legacy single-version registration (served under the
    # core route, and under /apis/<group>/v1 when a group is set)
    versions: List[CRDVersion] = field(default_factory=list)
    scope: str = "Namespaced"  # Namespaced | Cluster
    # opaque openAPIV3Schema-style validation payload (stored, not
    # enforced — the reference's structural-schema validation is a
    # non-goal for the scheduling framework)
    schema: Dict[str, Any] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class CustomObject:
    """An instance of a runtime-registered kind (the dynamic client's
    unstructured object): typed metadata + opaque spec/status payloads."""

    kind: str = ""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: Dict[str, Any] = field(default_factory=dict)
    status: Dict[str, Any] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


# ---------------------------------------------------------------------------
# Admission webhook registration (reference
# staging/src/k8s.io/api/admissionregistration/v1/types.go; dispatched by
# staging/.../admission/plugin/webhook/{mutating,validating}/dispatcher.go)


@dataclass
class WebhookRule:
    """Which (operations x resources) a webhook intercepts
    (admissionregistration RuleWithOperations; "*" wildcards)."""

    operations: List[str] = field(default_factory=lambda: ["*"])
    resources: List[str] = field(default_factory=lambda: ["*"])


@dataclass
class Webhook:
    """One registered hook: where to POST the AdmissionReview and how to
    treat call failures (failurePolicy Fail|Ignore, reference
    v1.FailurePolicyType)."""

    name: str = ""
    url: str = ""
    rules: List[WebhookRule] = field(default_factory=list)
    failure_policy: str = "Fail"  # Fail | Ignore
    timeout_seconds: int = 10


@dataclass
class MutatingWebhookConfiguration:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: List[Webhook] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class ValidatingWebhookConfiguration:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: List[Webhook] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------------------
# Secrets / ConfigMaps / CertificateSigningRequests (core/v1 Secret +
# ConfigMap; certificates.k8s.io/v1 CSR) — the object surface the
# certificate and bootstrap-token controller families reconcile.


@dataclass
class Secret:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    type: str = "Opaque"
    # values kept as plain strings (the reference carries base64 bytes
    # on the wire; the framework's store is in-process)
    data: Dict[str, str] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class CSRCondition:
    type: str = ""       # Approved | Denied | Failed
    reason: str = ""
    message: str = ""
    timestamp: float = 0.0


@dataclass
class CertificateSigningRequest:
    """certificates.k8s.io/v1 CSR: spec.request (PEM CSR) + signerName;
    approval is a status condition; the signer fills
    status.certificate."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    request: str = ""       # the CSR payload (PEM in the reference)
    signer_name: str = ""
    username: str = ""
    usages: List[str] = field(default_factory=list)
    conditions: List[CSRCondition] = field(default_factory=list)
    certificate: str = ""   # issued cert (status.certificate)

    @property
    def name(self) -> str:
        return self.metadata.name

    def condition(self, type_: str) -> Optional[CSRCondition]:
        for c in self.conditions:
            if c.type == type_:
                return c
        return None

    @property
    def approved(self) -> bool:
        return self.condition("Approved") is not None

    @property
    def denied(self) -> bool:
        return self.condition("Denied") is not None
