"""Host-path microprofiler: where does each pod's admission + commit
time go? Runs entirely on CPU with the C++ planes backend so the device
side is cheap and the HOST costs (BASELINE.md: commit ~70µs/pod,
admission ~117µs/pod) dominate and are attributable.

Usage:  python tools/profile_host.py [--nodes 1000] [--pods 10000] [--cprofile]
"""

from __future__ import annotations

import argparse
import cProfile
import os
import pstats
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("KTPU_SOLVER", "cpp")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.harness import make_workload, run_workload  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1000)
    ap.add_argument("--pods", type=int, default=10000)
    ap.add_argument("--cprofile", action="store_true")
    ap.add_argument("--sort", default="cumulative")
    ap.add_argument("--limit", type=int, default=45)
    args = ap.parse_args()

    ops = make_workload("SchedulingBasic", nodes=args.nodes, init_pods=0,
                        measure_pods=args.pods)

    def run():
        return run_workload("profile", ops, use_batch=True,
                            max_batch=8192, wait_timeout=600,
                            progress=lambda m: print(m, file=sys.stderr))

    if args.cprofile:
        prof = cProfile.Profile()
        t0 = time.time()
        result = prof.runcall(run)
        wall = time.time() - t0
        stats = pstats.Stats(prof)
        stats.sort_stats(args.sort).print_stats(args.limit)
    else:
        t0 = time.time()
        result = run()
        wall = time.time() - t0
    print(f"pods/s={result.pods_per_second:.0f} wall={wall:.1f}s "
          f"measured={result.measured_pods}", file=sys.stderr)


if __name__ == "__main__":
    main()
