"""Human-readable SLO status table from a live server or a committed
bench artifact.

Two sources, one table:

- ``--url http://host:port`` GETs ``/debug/slo`` (the SLO engine's
  live evaluation — burn rates, windowed SLIs, verdicts) and renders
  each SLO's row;
- ``--artifact BENCH_rN.json`` (or a bare bench-row JSON-lines file)
  reads the driver-committed artifact, pulls every bench row's
  ``freshness`` sub-object (watch-delivery p99, max snapshot
  staleness, SLO verdicts) and renders the per-row verdict table —
  the SLI layer's numbers without re-running anything.

Usage::

    python tools/slo_report.py --url http://127.0.0.1:8080
    python tools/slo_report.py --artifact BENCH_r08.json
    python tools/slo_report.py --artifact BENCH_r08.json --strict
    python tools/slo_report.py --url ... --json   # machine-readable

``--strict`` exits 1 when any SLO is violated (CI gating). Output goes
to stdout; ``--out FILE`` tees it to a scratch file (gitignored —
telemetry runs must not re-pollute the tree).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)


# ---------------------------------------------------------------------------
# sources


def fetch_live(url: str, timeout: float = 5.0) -> dict:
    """GET /debug/slo from a live server (control-plane envelope:
    loopback on a tokenless server needs no token)."""
    import http.client

    rest = url.rstrip("/").split("://", 1)[-1]
    host, _, port = rest.partition(":")
    conn = http.client.HTTPConnection(host, int(port or 80),
                                      timeout=timeout)
    try:
        conn.request("GET", "/debug/slo")
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise RuntimeError(
                f"/debug/slo answered HTTP {resp.status}: "
                f"{body[:200]!r}")
        return json.loads(body)
    finally:
        conn.close()


def rows_from_artifact(path: str) -> List[dict]:
    """Bench rows (with their ``freshness`` sub-objects) from a
    driver-committed BENCH_r*.json artifact, or from a plain file of
    bench-row JSON lines."""
    with open(path) as f:
        raw = f.read()
    try:
        doc = json.loads(raw)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc:
        from tools.perf_report import _rows_from_tail

        return _rows_from_tail(doc["tail"])
    rows = []
    for line in raw.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict) and "metric" in row:
            rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# rendering


def _fmt_live(doc: dict) -> tuple:
    """(text, violated_names) for a live /debug/slo evaluation."""
    lines = []
    violated = []
    slos = doc.get("slos", {})
    if not doc.get("enabled", True):
        return "SLO evaluation disabled (KTPU_SLO=off)\n", []
    header = (f"{'SLO':<22} {'verdict':<9} {'burn fast':>9} "
              f"{'burn slow':>9} {'budget':>8} {'events':>8}  sli")
    lines.append(header)
    lines.append("-" * len(header))
    for name, s in sorted(slos.items()):
        verdict = "VIOLATED" if s.get("violated") else (
            "alerting" if s.get("alerting") else "ok")
        if s.get("violated"):
            violated.append(name)
        sli = ""
        if "sli_fast_p99_s" in s:
            sli = (f"p99 {s['sli_fast_p99_s'] * 1000:.0f}ms "
                   f"(obj ≤{s.get('threshold_s', 0) * 1000:.0f}ms)")
        elif s.get("kind") == "error_ratio":
            ev = s.get("events_fast") or 0
            bad = s.get("bad_fast") or 0
            sli = f"{bad:.0f}/{ev:.0f} rejected"
        lines.append(
            f"{name:<22} {verdict:<9} {s.get('burn_fast', 0):>9.2f} "
            f"{s.get('burn_slow', 0):>9.2f} "
            f"{s.get('budget_remaining_pct', 100):>7.1f}% "
            f"{s.get('events_fast', 0):>8.0f}  {sli}")
    lines.append("")
    lines.append("healthy" if doc.get("healthy") else
                 f"UNHEALTHY: {', '.join(violated)}")
    return "\n".join(lines) + "\n", violated


def _fmt_rows(rows: List[dict]) -> tuple:
    """(text, violated_names) for committed bench rows' freshness
    sub-objects."""
    lines = []
    violated = []
    any_fresh = False
    header = (f"{'bench row':<58} {'wd p99':>8} {'stale max':>10}  "
              f"slo verdicts")
    lines.append(header)
    lines.append("-" * len(header))
    for row in rows:
        fresh = row.get("freshness")
        if not fresh:
            continue
        any_fresh = True
        metric = row.get("metric", "?")
        short = metric[:56] + ".." if len(metric) > 58 else metric
        wd = fresh.get("watch_delivery_p99_ms")
        stale = fresh.get("max_snapshot_staleness_ms",
                          fresh.get("snapshot_staleness_p99_ms"))
        verdicts = fresh.get("slo") or {}
        bad = sorted(n for n, v in verdicts.items() if v != "ok")
        violated.extend(bad)
        vtext = " ".join(
            f"{n}={'VIOLATED' if v != 'ok' else 'ok'}"
            for n, v in sorted(verdicts.items())) or "-"
        lines.append(
            f"{short:<58} "
            f"{(f'{wd:.1f}ms' if wd is not None else '-'):>8} "
            f"{(f'{stale:.0f}ms' if stale is not None else '-'):>10}  "
            f"{vtext}")
    if not any_fresh:
        lines.append("(no rows carry a freshness sub-object — "
                     "pre-SLI artifact?)")
    lines.append("")
    lines.append("healthy" if not violated else
                 f"UNHEALTHY: {', '.join(sorted(set(violated)))}")
    return "\n".join(lines) + "\n", sorted(set(violated))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="SLO status table from /debug/slo or a bench "
                    "artifact")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="live server base URL")
    src.add_argument("--artifact", help="BENCH_r*.json or bench-row "
                                        "JSON-lines file")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any SLO is violated")
    ap.add_argument("--out", help="also write the report to this file "
                                  "(scratch output, gitignored)")
    args = ap.parse_args(argv)

    if args.url:
        doc = fetch_live(args.url)
        if args.json:
            text = json.dumps(doc, indent=2) + "\n"
            violated = [n for n, s in doc.get("slos", {}).items()
                        if s.get("violated")]
        else:
            text, violated = _fmt_live(doc)
    else:
        rows = rows_from_artifact(args.artifact)
        if args.json:
            fresh = [{"metric": r.get("metric"),
                      "freshness": r.get("freshness")}
                     for r in rows if r.get("freshness")]
            violated = sorted({
                n for r in rows
                for n, v in ((r.get("freshness") or {}).get("slo")
                             or {}).items() if v != "ok"})
            text = json.dumps({"rows": fresh,
                               "violated": violated}, indent=2) + "\n"
        else:
            text, violated = _fmt_rows(rows)

    sys.stdout.write(text)
    if args.out:
        try:
            with open(args.out, "w") as f:
                f.write(text)
        except OSError as e:
            print(f"--out failed: {e}", file=sys.stderr)
    return 1 if (args.strict and violated) else 0


if __name__ == "__main__":
    raise SystemExit(main())
