#!/usr/bin/env python
"""Run the seeded chaos-over-REST fault matrix and print a pass/fail
table (the CI face of ``kubernetes_tpu.harness.chaos_rest``).

Each cell is one ``run_chaos_rest`` invocation: a seeded fault profile
armed through /debug/faults, an apiserver SIGKILL + WAL-restore restart
mid-workload, and the chaos invariants (all bound exactly once, no
oversubscription, WAL == live, no resourceVersion regression) checked
after quiescence.

Usage::

    python tools/chaos_matrix.py                      # default matrix
    python tools/chaos_matrix.py --seeds 11,23 --profiles mixed,resets
    python tools/chaos_matrix.py --pods 240 --nodes 40 -v

Exit status is non-zero when any cell fails.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(
        description="seeded chaos-over-REST matrix")
    parser.add_argument("--seeds", default="11,23,37,41,53",
                        help="comma-separated chaos seeds")
    parser.add_argument("--profiles", default="mixed",
                        help="comma-separated fault profiles "
                             "(mixed,resets,pushback,watchstorm)")
    parser.add_argument("--nodes", type=int, default=20)
    parser.add_argument("--pods", type=int, default=120)
    parser.add_argument("--wait-timeout", type=float, default=120.0)
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="stream per-run progress")
    args = parser.parse_args()

    # keep the scheduler on the CPU mesh: the matrix measures the wire,
    # not the solver
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from kubernetes_tpu.harness.chaos_rest import (
        FAULT_PROFILES,
        run_chaos_rest,
    )

    seeds = [int(s) for s in args.seeds.split(",") if s]
    profiles = [p for p in args.profiles.split(",") if p]
    for p in profiles:
        if p not in FAULT_PROFILES:
            parser.error(f"unknown profile {p!r} "
                         f"(have: {', '.join(sorted(FAULT_PROFILES))})")

    progress = print if args.verbose else None
    rows = []
    failed = 0
    for profile in profiles:
        for seed in seeds:
            t0 = time.monotonic()
            try:
                r = run_chaos_rest(
                    seed, nodes=args.nodes, pods=args.pods,
                    fault_profile=profile,
                    wait_timeout=args.wait_timeout, progress=progress)
            except Exception as e:  # noqa: BLE001 — a crashed run is a FAIL row
                r = {"seed": seed, "profile": profile, "ok": False,
                     "failure": f"{type(e).__name__}: {e}", "stats": {}}
            r["elapsed"] = time.monotonic() - t0
            rows.append(r)
            if not r["ok"]:
                failed += 1
            status = "PASS" if r["ok"] else "FAIL"
            print(f"  [{status}] {profile}/seed={seed} "
                  f"({r['elapsed']:.1f}s)", flush=True)

    head = (f"{'profile':<12} {'seed':>5} {'result':<6} {'faults':>7} "
            f"{'retries':>8} {'degraded_s':>10} {'time':>7}  failure")
    print()
    print(head)
    print("-" * len(head))
    for r in rows:
        s = r.get("stats") or {}
        print(f"{r['profile']:<12} {r['seed']:>5} "
              f"{'PASS' if r['ok'] else 'FAIL':<6} "
              f"{s.get('faults_injected', '-'):>7} "
              f"{s.get('client_retries', '-'):>8} "
              f"{s.get('degraded_seconds', '-'):>10} "
              f"{r['elapsed']:>6.1f}s  {r.get('failure', '')}")
    print(f"\n{len(rows) - failed}/{len(rows)} cells passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
