#!/usr/bin/env python
"""Run the seeded chaos matrices and print a pass/fail table (the CI
face of ``kubernetes_tpu.harness.chaos_rest`` and ``chaos_nodes``).

The suites:

- ``rest`` — wire-level: a seeded fault profile armed through
  /debug/faults, an apiserver SIGKILL + WAL-restore restart
  mid-workload, invariants (all bound exactly once, no
  oversubscription, WAL == live, no resourceVersion regression)
  checked after quiescence.
- ``nodes`` — node churn: a seeded injector kills/flaps/cordons/taints
  nodes while the workload streams in over REST, with the
  nodelifecycle controller evicting and the rescue pipeline
  recreating; invariants (no binds to dead nodes, no lost pods,
  cache == store after quiesce) plus rescue-latency p99 per cell.
- ``scale`` — elasticity: burst-size × boot-latency cells through the
  cluster autoscaler (cluster starts at 20% of needed capacity, the
  what-if solver buys the rest); each cell reports time-to-capacity
  p99 across repeats and fails on any unbound pod.
- ``overload`` — multi-tenant abuse against API Priority & Fairness:
  aggressor tenants mount list storms / watch reconnect herds /
  bulk-verb abuse / full seat saturation (seeded read-latency via the
  FaultGate makes queues form) while a victim tenant's pods must all
  bind; invariants: zero lost pods, exempt routes always served, no
  starved flow, per-object rate equivalence for bulk verbs.
- ``replay`` — trace-replay scenario families (storm / gangs /
  tenancy): compressed open-loop mini-replays per (family × seed)
  with seeded heavy-tailed arrivals and lifetime churn; invariants:
  zero lost pods, gang atomicity (never a partially-placed gang), no
  priority inversion at quiesce.
- ``reshard`` — live partition resharding: slice migrations under a
  seeded write storm (``midstorm``), a REAL partition server
  SIGKILLed at a seeded phase of a live migration — must roll back or
  complete, never half-routed — then WAL-restored and re-pointed
  (``sigkill``), and the load-aware rebalancer under a hot-namespace
  storm (``rebalance``); invariants: zero lost pods, no
  double-delivered watch events, cache ≡ store at quiesce, one
  topology epoch fleet-wide.
- ``upgrade`` — rolling upgrades: the WHOLE fleet (spawned partition
  servers + scheduler replicas) restarted exactly once each under
  sustained open-loop load, crossing roll order (``partitions-first``
  / ``schedulers-first``) × SIGKILL mid-roll on the draining process
  (``sigkill-*``); per partition: freeze → drain → verify → promote a
  prespawned standby → reroute, abort-and-rollback if the drain blows
  its budget; invariants: every roll complete-or-rolled-back, zero
  lost pods, zero lost/duplicated watch events, zero relists of
  unmoved slices, one epoch, and a v1-pinned client held at codec v1
  across every seam (mixed-version wire guard).
- ``mirror`` — device-resident cluster-state cells: the same seeded
  event sequence run mirror-on (watch deltas scattered into the
  donated resident planes) and ``KTPU_MIRROR=off`` (the delta-encode
  reference), crossing a node killed inside the scatter window, a
  mesh resize with pods in flight, and an event storm overflowing the
  delta journal (which MUST surface as a reseed); invariants:
  bit-identical placement sets across arms, zero lost pods.
- ``federation`` — federated multi-cluster cells: K independent
  spawned clusters (each its own apiserver + scheduler) behind the
  federation tier, crossing saturation spillover (``spill`` — one
  cell pinned past capacity, overflow must land remotely with the
  saturated cell's own SLOs green) × whole-cluster SIGKILL at
  25/50/75% of the storm (``loss-early``/``loss-mid``/``loss-late``,
  or ``spill-loss`` for both at once); invariants: zero lost pods
  fleet-wide, every orphan re-placed onto survivors within the
  recovery budget, relists confined to the dead cell, gangs never
  split across clusters.

Usage::

    python tools/chaos_matrix.py                      # rest + nodes
    python tools/chaos_matrix.py --suite nodes --churn mixed,killer
    python tools/chaos_matrix.py --suite rest --seeds 11,23 -v
    python tools/chaos_matrix.py --suite scale --bursts 60,120 -v
    python tools/chaos_matrix.py --suite overload -v
    python tools/chaos_matrix.py --suite overload \
        --overload liststorm,saturation --seeds 11,23
    python tools/chaos_matrix.py --suite replay --families storm,gangs
    python tools/chaos_matrix.py --suite reshard --seeds 11,23,37
    python tools/chaos_matrix.py --suite upgrade --seeds 3,5 \
        --upgrade partitions-first,sigkill-schedulers-first
    python tools/chaos_matrix.py --suite federation --seeds 18 \
        --federation spill,loss-mid
    python tools/chaos_matrix.py --suite mirror --seeds 11,23 \
        --mirror node_kill,event_storm
    python tools/chaos_matrix.py --pods 240 --nodes 40 -v

Exit status is non-zero when any cell fails.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _run_suite(args, progress, rows, suite: str, run_fn,
               profile_kw: str, profiles) -> None:
    seeds = [int(s) for s in args.seeds.split(",") if s]
    for profile in profiles:
        for seed in seeds:
            t0 = time.monotonic()
            try:
                r = run_fn(seed, nodes=args.nodes, pods=args.pods,
                           wait_timeout=args.wait_timeout,
                           progress=progress, **{profile_kw: profile})
            except Exception as e:  # noqa: BLE001 — a crashed run is a FAIL row
                r = {"seed": seed, "profile": profile, "ok": False,
                     "failure": f"{type(e).__name__}: {e}", "stats": {}}
            r["suite"] = suite
            r["elapsed"] = time.monotonic() - t0
            rows.append(r)
            status = "PASS" if r["ok"] else "FAIL"
            print(f"  [{status}] {suite}/{profile}/seed={seed} "
                  f"({r['elapsed']:.1f}s)", flush=True)


def main() -> int:
    parser = argparse.ArgumentParser(
        description="seeded chaos matrices (wire faults + node churn)")
    parser.add_argument("--suite", default="both",
                        choices=("rest", "nodes", "scale", "overload",
                                 "partition", "replay", "reshard",
                                 "upgrade", "federation", "readtier",
                                 "mirror", "both", "all"))
    parser.add_argument("--seeds", default="11,23,37,41,53",
                        help="comma-separated chaos seeds")
    parser.add_argument("--profiles", default="mixed",
                        help="rest-suite fault profiles "
                             "(mixed,resets,pushback,watchstorm)")
    parser.add_argument("--churn", default="mixed",
                        help="nodes-suite churn profiles "
                             "(mixed,killer,flappy,gentle)")
    parser.add_argument("--overload", default="mixed",
                        help="overload-suite abuse shapes (liststorm,"
                             "watchherd,bulkabuse,saturation,mixed)")
    parser.add_argument("--families", default="storm,gangs,tenancy",
                        help="replay-suite scenario families "
                             "(storm,gangs,tenancy)")
    parser.add_argument("--reshard", default="midstorm,sigkill,rebalance",
                        help="reshard-suite scenarios "
                             "(midstorm,sigkill,rebalance)")
    parser.add_argument("--upgrade",
                        default="partitions-first,schedulers-first,"
                                "sigkill-partitions-first,"
                                "sigkill-schedulers-first",
                        help="upgrade-suite roll scenarios: roll order "
                             "(partitions-first,schedulers-first) × "
                             "SIGKILL mid-roll on a draining process "
                             "(sigkill-* variants)")
    parser.add_argument("--federation",
                        default="spill,loss-mid",
                        help="federation-suite scenarios: saturation "
                             "spillover (spill), whole-cluster SIGKILL "
                             "at 25/50/75%% of the storm "
                             "(loss-early,loss-mid,loss-late), or both "
                             "at once (spill-loss)")
    parser.add_argument("--readtier",
                        default="replica_kill,owner_restart,lag_fence",
                        help="readtier-suite scenarios: read-replica "
                             "SIGKILL mid-herd (replica_kill), owner "
                             "SIGKILL + same-port WAL restart with "
                             "replicas live (owner_restart), or a "
                             "slow replica blowing its lag budget "
                             "(lag_fence)")
    parser.add_argument("--mirror",
                        default="node_kill,mesh_resize,event_storm",
                        help="mirror-suite scenarios: a node killed "
                             "inside the scatter window (node_kill), "
                             "a mesh resize with pods in flight "
                             "(mesh_resize), or an event storm "
                             "overflowing the delta journal — must "
                             "force a reseed (event_storm)")
    parser.add_argument("--nodes", type=int, default=20)
    parser.add_argument("--pods", type=int, default=120)
    parser.add_argument("--wait-timeout", type=float, default=120.0)
    parser.add_argument("--bursts", default="60,120",
                        help="scale-suite burst sizes (pods per cell)")
    parser.add_argument("--boots", default="0.0,0.3",
                        help="scale-suite provisioner boot latencies (s)")
    parser.add_argument("--scale-repeats", type=int, default=2,
                        help="elastic runs per scale cell (p99 basis)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="stream per-run progress")
    args = parser.parse_args()

    # keep the scheduler on the CPU mesh: the matrix measures the
    # fabric and the churn, not the solver
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.suite in ("mirror", "all"):
        # the mirror suite's mesh-resize cell wants a multi-device CPU
        # mesh; the flag only lands if it is set before the first jax
        # import (the cell degrades to the available width otherwise)
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")

    from kubernetes_tpu.harness.chaos_rest import FAULT_PROFILES
    from kubernetes_tpu.harness.chaos_nodes import CHURN_PROFILES
    from kubernetes_tpu.harness.chaos_overload import OVERLOAD_PROFILES

    for p in args.profiles.split(","):
        if p and p not in FAULT_PROFILES:
            parser.error(f"unknown fault profile {p!r} "
                         f"(have: {', '.join(sorted(FAULT_PROFILES))})")
    for p in args.churn.split(","):
        if p and p not in CHURN_PROFILES:
            parser.error(f"unknown churn profile {p!r} "
                         f"(have: {', '.join(sorted(CHURN_PROFILES))})")
    for p in args.overload.split(","):
        if p and p not in OVERLOAD_PROFILES:
            parser.error(f"unknown overload profile {p!r} "
                         f"(have: {', '.join(sorted(OVERLOAD_PROFILES))})")
    from kubernetes_tpu.workloads.scenarios import REPLAY_FAMILIES

    for p in args.families.split(","):
        if p and p not in REPLAY_FAMILIES:
            parser.error(f"unknown replay family {p!r} "
                         f"(have: {', '.join(sorted(REPLAY_FAMILIES))})")
    from kubernetes_tpu.harness.chaos_reshard import RESHARD_SCENARIOS

    for p in args.reshard.split(","):
        if p and p not in RESHARD_SCENARIOS:
            parser.error(f"unknown reshard scenario {p!r} "
                         f"(have: {', '.join(sorted(RESHARD_SCENARIOS))})")
    from kubernetes_tpu.harness.upgrade import UPGRADE_SCENARIOS

    for p in args.upgrade.split(","):
        if p and p not in UPGRADE_SCENARIOS:
            parser.error(f"unknown upgrade scenario {p!r} "
                         f"(have: {', '.join(sorted(UPGRADE_SCENARIOS))})")
    from kubernetes_tpu.harness.federation import FEDERATION_SCENARIOS

    for p in args.federation.split(","):
        if p and p not in FEDERATION_SCENARIOS:
            parser.error(
                f"unknown federation scenario {p!r} "
                f"(have: {', '.join(sorted(FEDERATION_SCENARIOS))})")
    from kubernetes_tpu.harness.chaos_mirror import MIRROR_SCENARIOS

    for p in args.mirror.split(","):
        if p and p not in MIRROR_SCENARIOS:
            parser.error(
                f"unknown mirror scenario {p!r} "
                f"(have: {', '.join(MIRROR_SCENARIOS)})")
    from kubernetes_tpu.harness.watchherd import READTIER_SCENARIOS

    for p in args.readtier.split(","):
        if p and p not in READTIER_SCENARIOS:
            parser.error(
                f"unknown readtier scenario {p!r} "
                f"(have: {', '.join(sorted(READTIER_SCENARIOS))})")

    from kubernetes_tpu.harness.chaos_nodes import run_chaos_nodes
    from kubernetes_tpu.harness.chaos_rest import run_chaos_rest

    progress = print if args.verbose else None
    rows = []
    if args.suite in ("rest", "both"):
        _run_suite(args, progress, rows, "rest", run_chaos_rest,
                   "fault_profile",
                   [p for p in args.profiles.split(",") if p])
    if args.suite in ("nodes", "both", "all"):
        _run_suite(args, progress, rows, "nodes", run_chaos_nodes,
                   "churn_profile",
                   [p for p in args.churn.split(",") if p])
    if args.suite in ("overload", "all"):
        from kubernetes_tpu.harness.chaos_overload import (
            run_chaos_overload,
        )

        _run_suite(args, progress, rows, "overload", run_chaos_overload,
                   "overload_profile",
                   [p for p in args.overload.split(",") if p])
    if args.suite in ("replay", "all"):
        # trace-replay scenario cells: compressed mini-replays per
        # (family × seed) with the family invariants as pass/fail —
        # zero lost pods, gang atomicity (never a partially-placed
        # gang), no priority inversion at quiesce
        from kubernetes_tpu.workloads import run_replay_cell

        _run_suite(args, progress, rows, "replay", run_replay_cell,
                   "family",
                   [f for f in args.families.split(",") if f])
    if args.suite in ("reshard", "all"):
        # live-resharding cells: migrations mid-storm, partition
        # SIGKILL mid-migration (rollback or completion, never a torn
        # routing table), rebalancer-under-storm — the elastic control
        # plane's invariants as pass/fail
        from kubernetes_tpu.harness.chaos_reshard import (
            run_chaos_reshard,
        )

        _run_suite(args, progress, rows, "reshard", run_chaos_reshard,
                   "scenario",
                   [s for s in args.reshard.split(",") if s])
    if args.suite in ("upgrade", "all"):
        # rolling-upgrade cells: the whole fleet (spawned partition
        # servers + scheduler replicas) restarted one process at a
        # time under load, crossing roll order × SIGKILL mid-roll on
        # the draining process — every roll must complete-or-rollback
        # with zero lost pods/events and the mixed-version wire guard
        # holding a v1-pinned client across every seam
        from kubernetes_tpu.harness.upgrade import run_chaos_upgrade

        _run_suite(args, progress, rows, "upgrade", run_chaos_upgrade,
                   "scenario",
                   [s for s in args.upgrade.split(",") if s])
    if args.suite in ("federation", "all"):
        # federated multi-cluster cells: K independent spawned
        # clusters behind the federation tier, crossing saturation
        # spillover (one cell pinned past capacity, overflow must land
        # remotely with the saturated cell's SLOs green) × whole-
        # cluster SIGKILL mid-storm (every orphan re-placed onto
        # survivors, zero lost fleet-wide, relists confined to the
        # dead cell, gangs never split across clusters)
        from kubernetes_tpu.harness.federation import (
            run_chaos_federation,
        )

        _run_suite(args, progress, rows, "federation",
                   run_chaos_federation, "scenario",
                   [s for s in args.federation.split(",") if s])
    if args.suite in ("readtier", "all"):
        # read-tier cells: a spawned owner + read replicas serving an
        # informer herd through a live writer, crossing replica
        # SIGKILL mid-herd (relists confined to the dead replica,
        # zero lost fleet-wide) × owner SIGKILL + same-port WAL
        # restart (replicas resubscribe from their cursor — no full
        # reseed, replica-served streams never break) × a slow
        # replica blowing its lag budget (self-fence, clients
        # re-route, relists confined)
        from kubernetes_tpu.harness.watchherd import run_chaos_readtier

        _run_suite(args, progress, rows, "readtier",
                   run_chaos_readtier, "scenario",
                   [s for s in args.readtier.split(",") if s])
    if args.suite in ("mirror", "all"):
        # device-mirror cells: the same seeded sequence run scatter-on
        # vs delta-encode-off across the mirror's fault seams — a node
        # killed inside the scatter window, a mesh resize with pods in
        # flight, an event storm overflowing the delta journal (which
        # must force a reseed, never silently drop deltas); verdict =
        # bit-identical placements across arms + zero lost pods
        from kubernetes_tpu.harness.chaos_mirror import run_chaos_mirror

        _run_suite(args, progress, rows, "mirror", run_chaos_mirror,
                   "scenario",
                   [s for s in args.mirror.split(",") if s])
    if args.suite in ("partition", "all"):
        # partitioned-control-plane conflict cells: replica sets with
        # overlapping responsibility racing over a tight cluster — the
        # bind CAS + capacity guards must resolve every collision
        # (conflicts REQUIRED: a quiet cell proved nothing), with zero
        # lost pods and zero double-binds/oversubscription
        from kubernetes_tpu.harness.scale import run_conflict_cell

        for shape, (p_count, r_count) in (("2px2r", (2, 2)),
                                          ("1px3r", (1, 3)),
                                          ("4px2r", (4, 2))):
            t0 = time.monotonic()
            try:
                # 2-cpu nodes, 500m pods: 4 slots per node; fill to
                # 2 short of capacity so every brain races over an
                # almost-full cluster but the burst still fits
                cell_nodes = max(8, args.nodes // 2)
                r = run_conflict_cell(
                    nodes=cell_nodes, pods=cell_nodes * 4 - 2,
                    partitions=p_count, replicas=r_count,
                    progress=progress)
                r.setdefault("stats", {
                    "conflicts": r.get("conflicts_total", 0)})
            except Exception as e:  # noqa: BLE001 — crashed cell = FAIL
                r = {"ok": False,
                     "failure": f"{type(e).__name__}: {e}", "stats": {}}
            r["suite"] = "partition"
            r["profile"] = shape
            r["seed"] = "-"
            r["elapsed"] = time.monotonic() - t0
            rows.append(r)
            status = "PASS" if r["ok"] else "FAIL"
            print(f"  [{status}] partition/{shape} "
                  f"({r['elapsed']:.1f}s)", flush=True)

    if args.suite in ("scale", "all"):
        from kubernetes_tpu.harness.elastic import run_scale_cell

        bursts = [int(b) for b in args.bursts.split(",") if b]
        boots = [float(b) for b in args.boots.split(",") if b != ""]
        for burst in bursts:
            for boot in boots:
                cell = f"b{burst}/bl{boot:g}"
                t0 = time.monotonic()
                try:
                    r = run_scale_cell(
                        burst, boot, repeats=args.scale_repeats,
                        node_cpu=4, wait_timeout=args.wait_timeout,
                        progress=progress)
                except Exception as e:  # noqa: BLE001 — crashed cell = FAIL
                    r = {"ok": False,
                         "failure": f"{type(e).__name__}: {e}",
                         "stats": {}}
                r["suite"] = "scale"
                r["profile"] = cell
                r["seed"] = "-"
                r["elapsed"] = time.monotonic() - t0
                rows.append(r)
                status = "PASS" if r["ok"] else "FAIL"
                print(f"  [{status}] scale/{cell} "
                      f"({r['elapsed']:.1f}s)", flush=True)

    failed = sum(1 for r in rows if not r["ok"])
    head = (f"{'suite':<6} {'profile':<10} {'seed':>5} {'result':<6} "
            f"{'faults':>7} {'retries':>8} {'evict':>6} {'rescue_p99':>10} "
            f"{'time':>7}  failure")
    chaos_rows = [r for r in rows if r["suite"] != "scale"]
    if chaos_rows:
        print()
        print(head)
        print("-" * len(head))
        for r in chaos_rows:
            s = r.get("stats") or {}
            rescue_p99 = s.get("rescue_p99_s")
            print(f"{r['suite']:<6} {r['profile']:<10} {r['seed']:>5} "
                  f"{'PASS' if r['ok'] else 'FAIL':<6} "
                  f"{s.get('faults_injected', '-'):>7} "
                  f"{s.get('client_retries', '-'):>8} "
                  f"{s.get('evictions', '-'):>6} "
                  f"{(f'{rescue_p99:.3f}s' if rescue_p99 is not None else '-'):>10} "
                  f"{r['elapsed']:>6.1f}s  {r.get('failure', '')}")
    scale_rows = [r for r in rows if r["suite"] == "scale"]
    if scale_rows:
        head2 = (f"{'cell':<12} {'result':<6} {'ttc_p99':>8} "
                 f"{'ttc_p50':>8} {'pods/s':>8} {'scaleups':>9} "
                 f"{'nodes':>6} {'time':>7}  failure")
        print()
        print(head2)
        print("-" * len(head2))
        for r in scale_rows:
            s = r.get("stats") or {}
            p99 = s.get("time_to_capacity_p99_s")
            p50 = s.get("time_to_capacity_p50_s")
            print(f"{r['profile']:<12} "
                  f"{'PASS' if r['ok'] else 'FAIL':<6} "
                  f"{(f'{p99:.2f}s' if p99 is not None else '-'):>8} "
                  f"{(f'{p50:.2f}s' if p50 is not None else '-'):>8} "
                  f"{s.get('pods_per_s_min', 0.0):>8.1f} "
                  f"{s.get('scaleup_decisions', 0):>9} "
                  f"{s.get('nodes_provisioned', 0):>6} "
                  f"{r['elapsed']:>6.1f}s  {r.get('failure', '')}")
    print(f"\n{len(rows) - failed}/{len(rows)} cells passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
