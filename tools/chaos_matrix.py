#!/usr/bin/env python
"""Run the seeded chaos matrices and print a pass/fail table (the CI
face of ``kubernetes_tpu.harness.chaos_rest`` and ``chaos_nodes``).

Two suites:

- ``rest`` — wire-level: a seeded fault profile armed through
  /debug/faults, an apiserver SIGKILL + WAL-restore restart
  mid-workload, invariants (all bound exactly once, no
  oversubscription, WAL == live, no resourceVersion regression)
  checked after quiescence.
- ``nodes`` — node churn: a seeded injector kills/flaps/cordons/taints
  nodes while the workload streams in over REST, with the
  nodelifecycle controller evicting and the rescue pipeline
  recreating; invariants (no binds to dead nodes, no lost pods,
  cache == store after quiesce) plus rescue-latency p99 per cell.

Usage::

    python tools/chaos_matrix.py                      # both suites
    python tools/chaos_matrix.py --suite nodes --churn mixed,killer
    python tools/chaos_matrix.py --suite rest --seeds 11,23 -v
    python tools/chaos_matrix.py --pods 240 --nodes 40 -v

Exit status is non-zero when any cell fails.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _run_suite(args, progress, rows, suite: str, run_fn,
               profile_kw: str, profiles) -> None:
    seeds = [int(s) for s in args.seeds.split(",") if s]
    for profile in profiles:
        for seed in seeds:
            t0 = time.monotonic()
            try:
                r = run_fn(seed, nodes=args.nodes, pods=args.pods,
                           wait_timeout=args.wait_timeout,
                           progress=progress, **{profile_kw: profile})
            except Exception as e:  # noqa: BLE001 — a crashed run is a FAIL row
                r = {"seed": seed, "profile": profile, "ok": False,
                     "failure": f"{type(e).__name__}: {e}", "stats": {}}
            r["suite"] = suite
            r["elapsed"] = time.monotonic() - t0
            rows.append(r)
            status = "PASS" if r["ok"] else "FAIL"
            print(f"  [{status}] {suite}/{profile}/seed={seed} "
                  f"({r['elapsed']:.1f}s)", flush=True)


def main() -> int:
    parser = argparse.ArgumentParser(
        description="seeded chaos matrices (wire faults + node churn)")
    parser.add_argument("--suite", default="both",
                        choices=("rest", "nodes", "both"))
    parser.add_argument("--seeds", default="11,23,37,41,53",
                        help="comma-separated chaos seeds")
    parser.add_argument("--profiles", default="mixed",
                        help="rest-suite fault profiles "
                             "(mixed,resets,pushback,watchstorm)")
    parser.add_argument("--churn", default="mixed",
                        help="nodes-suite churn profiles "
                             "(mixed,killer,flappy,gentle)")
    parser.add_argument("--nodes", type=int, default=20)
    parser.add_argument("--pods", type=int, default=120)
    parser.add_argument("--wait-timeout", type=float, default=120.0)
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="stream per-run progress")
    args = parser.parse_args()

    # keep the scheduler on the CPU mesh: the matrix measures the
    # fabric and the churn, not the solver
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from kubernetes_tpu.harness.chaos_rest import FAULT_PROFILES
    from kubernetes_tpu.harness.chaos_nodes import CHURN_PROFILES

    for p in args.profiles.split(","):
        if p and p not in FAULT_PROFILES:
            parser.error(f"unknown fault profile {p!r} "
                         f"(have: {', '.join(sorted(FAULT_PROFILES))})")
    for p in args.churn.split(","):
        if p and p not in CHURN_PROFILES:
            parser.error(f"unknown churn profile {p!r} "
                         f"(have: {', '.join(sorted(CHURN_PROFILES))})")

    from kubernetes_tpu.harness.chaos_nodes import run_chaos_nodes
    from kubernetes_tpu.harness.chaos_rest import run_chaos_rest

    progress = print if args.verbose else None
    rows = []
    if args.suite in ("rest", "both"):
        _run_suite(args, progress, rows, "rest", run_chaos_rest,
                   "fault_profile",
                   [p for p in args.profiles.split(",") if p])
    if args.suite in ("nodes", "both"):
        _run_suite(args, progress, rows, "nodes", run_chaos_nodes,
                   "churn_profile",
                   [p for p in args.churn.split(",") if p])

    failed = sum(1 for r in rows if not r["ok"])
    head = (f"{'suite':<6} {'profile':<10} {'seed':>5} {'result':<6} "
            f"{'faults':>7} {'retries':>8} {'evict':>6} {'rescue_p99':>10} "
            f"{'time':>7}  failure")
    print()
    print(head)
    print("-" * len(head))
    for r in rows:
        s = r.get("stats") or {}
        rescue_p99 = s.get("rescue_p99_s")
        print(f"{r['suite']:<6} {r['profile']:<10} {r['seed']:>5} "
              f"{'PASS' if r['ok'] else 'FAIL':<6} "
              f"{s.get('faults_injected', '-'):>7} "
              f"{s.get('client_retries', '-'):>8} "
              f"{s.get('evictions', '-'):>6} "
              f"{(f'{rescue_p99:.3f}s' if rescue_p99 is not None else '-'):>10} "
              f"{r['elapsed']:>6.1f}s  {r.get('failure', '')}")
    print(f"\n{len(rows) - failed}/{len(rows)} cells passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
