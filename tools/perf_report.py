"""Perf-regression report over the committed bench trajectory.

Reads the driver-captured ``BENCH_r*.json`` artifacts (each holds the
stdout/stderr tail of one round's ``python bench.py`` run: per-run
``diag:`` lines interleaved with the per-family JSON rows), rebuilds
the per-family throughput/p99 trend, and judges every round-to-round
move against a measured NOISE BAND instead of eyeballing: the r3→r4
headline "regression" that turned out to be shared-tunnel variance is
the motivating case — a drop is only flagged when it falls OUTSIDE the
band the row's own repeat-runs establish.

When a drop IS flagged, the report attributes it to a phase: the row's
``telemetry`` sub-object (devprof: compile count, device-wait share,
pad waste, slowest-cycle phase) when present, else the round's parsed
``diag:`` phases compared against the previous round's — so the answer
to "what regressed" ships with the flag, not as a follow-up profiling
request.

Usage::

    python tools/perf_report.py                  # report over ./BENCH_r*.json
    python tools/perf_report.py --dir path/      # artifacts elsewhere
    python tools/perf_report.py --telemetry dir/ # + KTPU_TELEMETRY JSONL summary
    python tools/perf_report.py --strict         # exit 1 on any flagged regression
    python tools/perf_report.py --json           # machine-readable output

Runs as a tier-1 smoke over the committed artifacts
(tests/test_perf_report.py), so a malformed BENCH round or a schema
drift in the row JSON fails CI, not a human reading the trend table.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from kubernetes_tpu.harness.diagfmt import parse_diag  # noqa: E402

# relative spread floor: single-run rounds carry no within-row spread,
# but the shared TPU tunnel swings back-to-back runs by ±30% — a band
# narrower than that flags weather as regression (the r3→r4 case)
DEFAULT_NOISE_BAND = 0.30

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


# ---------------------------------------------------------------------------
# artifact loading


def _rows_from_tail(tail: str) -> List[dict]:
    """Per-family JSON rows in a driver tail, each annotated with the
    ``diag:`` lines of ITS runs (the diag lines print per run, the row
    JSON after the repeats — so the diags pending when a row line
    appears belong to that row)."""
    rows: List[dict] = []
    pending_diags: List[dict] = []
    for line in tail.splitlines():
        parsed = parse_diag(line)
        if parsed is not None:
            pending_diags.append(parsed)
            continue
        stripped = line.strip()
        if not stripped.startswith("{"):
            continue
        try:
            doc = json.loads(stripped)
        except ValueError:
            continue
        if not isinstance(doc, dict) or "metric" not in doc:
            continue
        doc["_diags"] = pending_diags
        pending_diags = []
        rows.append(doc)
    return rows


def load_round(path: str) -> dict:
    """One BENCH_r*.json artifact under the driver schema (``n``,
    ``cmd``, ``rc``, ``tail``, optional ``parsed``). Raises ValueError
    on schema drift — the tier-1 smoke turns that into a test failure."""
    with open(path) as f:
        doc = json.load(f)
    for key in ("n", "cmd", "rc", "tail"):
        if key not in doc:
            raise ValueError(f"{path}: missing driver key {key!r}")
    if not isinstance(doc["tail"], str):
        raise ValueError(f"{path}: tail is not a string")
    rows = _rows_from_tail(doc["tail"])
    if "parsed" in doc and isinstance(doc["parsed"], dict) \
            and doc["parsed"].get("metric"):
        metrics = {r["metric"] for r in rows}
        if doc["parsed"]["metric"] not in metrics:
            rows.append(dict(doc["parsed"], _diags=[]))
    return {"round": int(doc["n"]), "path": path, "rc": doc["rc"],
            "rows": rows}


def load_rounds(bench_dir: str) -> List[dict]:
    # the glob is wider than the round-name contract (BENCH_rest.json
    # would match it): only files the round regex accepts are rounds
    paths = [p for p in glob.glob(os.path.join(bench_dir,
                                               "BENCH_r*.json"))
             if _ROUND_RE.search(p)]
    paths.sort(key=lambda p: int(_ROUND_RE.search(p).group(1)))
    return [load_round(p) for p in paths]


def build_series(rounds: List[dict]) -> Dict[str, List[dict]]:
    """metric string → [{round, value, p99, runs, telemetry, diags}],
    round-ordered. The metric string IS the family key: it pins the
    workload, scale and path, so renamed scales never splice."""
    series: Dict[str, List[dict]] = {}
    for rnd in rounds:
        for row in rnd["rows"]:
            if row.get("unit") != "pods/s" or "error" in row:
                continue
            point = {
                "round": rnd["round"],
                "value": float(row.get("value", 0.0)),
                "p99_ms": row.get("p99_latency_ms",
                                  row.get("p99_arrival_to_bind_ms")),
                "runs": row.get("runs"),
                "telemetry": row.get("telemetry"),
                "diags": row.get("_diags", []),
            }
            if row.get("rate_normalized_throughput") is not None:
                # replay rows are OPEN-LOOP: raw pods/s tracks the
                # trace's offered rate, not the scheduler — the trend
                # (and regression detection) must compare bound-rate ÷
                # offered-rate, or a re-paced trace masquerades as a
                # perf move. Raw value kept for the table.
                point["raw_value"] = point["value"]
                point["value"] = float(
                    row["rate_normalized_throughput"])
            series.setdefault(row["metric"], []).append(point)
    for points in series.values():
        points.sort(key=lambda p: p["round"])
    return series


# ---------------------------------------------------------------------------
# noise band + regression detection


def noise_band(points: List[dict],
               floor: float = DEFAULT_NOISE_BAND) -> float:
    """Relative band from the rows' own repeat-runs (each ``runs``
    array is back-to-back samples of one round: its spread IS the
    run-to-run noise at that scale), floored at ``floor`` for rounds
    that ran single-shot."""
    band = 0.0
    for p in points:
        runs = p.get("runs")
        if runs and len(runs) >= 2 and p["value"] > 0:
            band = max(band, (max(runs) - min(runs)) / p["value"])
    return max(band, floor)


def _attribute(point: dict, prev: Optional[dict]) -> str:
    """Phase attribution for a flagged drop: devprof telemetry first
    (it names the slowest cycle's phase and the compile ledger), parsed
    diag phase totals vs the previous round second."""
    tel = point.get("telemetry")
    if tel:
        bits = []
        if tel.get("unexpected_compiles"):
            bits.append(
                f"{tel['unexpected_compiles']} compile(s) inside "
                f"measured cycles")
        mc = tel.get("max_cycle") or {}
        if mc.get("rebuild") not in (None, "none"):
            bits.append(f"max cycle did a {mc['rebuild']} rebuild")
        bits.append(
            f"device-wait share {tel.get('device_wait_share', 0.0):.0%}")
        if tel.get("pad_waste_pct", 0) > 25:
            bits.append(f"pad waste {tel['pad_waste_pct']:.0f}%")
        return "; ".join(bits)
    # legacy rounds: compare this row's diag phase totals against the
    # previous round's — the phase that grew the most is the suspect
    cur = _phase_totals(point)
    old = _phase_totals(prev) if prev else {}
    if not cur:
        return "no telemetry/diag in artifact"
    if not old:
        top = max(cur, key=cur.get)
        return f"dominant phase {top}={cur[top]:.2f}s (no prior round)"
    growth = {
        name: cur[name] - old.get(name, 0.0) for name in cur
    }
    top = max(growth, key=growth.get)
    return (f"phase {top} grew {old.get(top, 0.0):.2f}s -> "
            f"{cur[top]:.2f}s")


def _phase_totals(point: Optional[dict]) -> Dict[str, float]:
    if not point:
        return {}
    totals: Dict[str, float] = {}
    for diag in point.get("diags", []):
        for name, stats in (diag.get("phases") or {}).items():
            totals[name] = totals.get(name, 0.0) + stats["total_s"]
    return totals


def detect_regressions(series: Dict[str, List[dict]],
                       band_floor: float = DEFAULT_NOISE_BAND,
                       ) -> List[dict]:
    """Out-of-band drops, newest rounds judged against the median of
    the prior rounds (a single hot round must not become a baseline
    every later round 'regresses' from).

    Recovery (ISSUE 14): a flagged drop whose family LATER landed back
    inside the band it was judged against is history, not an open
    regression — the r5 GangScheduling flag must retire the round a
    fixed row is committed, without rewriting old artifacts. Such
    flags stay in the list carrying ``recovered_round`` (provenance
    for the report) but no longer gate ``--strict``
    (``open_regressions`` filters them)."""
    flags: List[dict] = []
    for metric, points in series.items():
        if len(points) < 2:
            continue
        for i in range(1, len(points)):
            # band from the PRIOR rounds only: a regression that also
            # blows up its own run-to-run variance (e.g. a recompile
            # landing in some runs) must not widen the band it is
            # judged against
            band = noise_band(points[:i], floor=band_floor)
            prior = sorted(p["value"] for p in points[:i])
            baseline = prior[len(prior) // 2]
            if baseline <= 0:
                continue
            delta = (points[i]["value"] - baseline) / baseline
            if delta < -band:
                flag = {
                    "metric": metric,
                    "round": points[i]["round"],
                    "value": points[i]["value"],
                    "baseline": baseline,
                    "delta_pct": round(100.0 * delta, 1),
                    "band_pct": round(100.0 * band, 1),
                    "attribution": _attribute(points[i], points[i - 1]),
                }
                floor_v = baseline * (1.0 - band)
                recovered = next(
                    (p["round"] for p in points[i + 1:]
                     if p["value"] >= floor_v), None)
                if recovered is not None:
                    flag["recovered_round"] = recovered
                flags.append(flag)
    return flags


def open_regressions(flags: List[dict]) -> List[dict]:
    """The flags that still gate ``--strict``: drops no later round
    has recovered from."""
    return [f for f in flags if "recovered_round" not in f]


# ---------------------------------------------------------------------------
# telemetry JSONL (KTPU_TELEMETRY) summary


def summarize_telemetry(telemetry_dir: str) -> dict:
    """Aggregate per-cycle JSONL records (one file per process) into
    the same shape as ``DevProfiler.summary()`` — so a bench row's
    committed sub-object can be cross-checked against the raw stream."""
    out = {"cycles": 0, "warming_cycles": 0, "compiles": 0,
           "unexpected_compiles": 0, "block_s": 0.0, "dispatch_s": 0.0,
           "encode_s": 0.0, "h2d_bytes": 0, "d2h_bytes": 0,
           "donated_bytes": 0, "real_rows": 0, "padded_rows": 0,
           "overlap_s": 0.0, "overlap_block_s": 0.0,
           "overlapped_cycles": 0, "files": 0}
    for path in sorted(glob.glob(
            os.path.join(telemetry_dir, "solvercycles-*.jsonl"))):
        out["files"] += 1
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("warming"):
                    out["warming_cycles"] += 1
                    continue
                out["cycles"] += 1
                out["compiles"] += rec.get("compiles", 0)
                if rec.get("compiles") and not rec.get("warming"):
                    out["unexpected_compiles"] += rec["compiles"]
                out["block_s"] += rec.get("block_s", 0.0)
                out["dispatch_s"] += rec.get("dispatch_s", 0.0)
                out["encode_s"] += rec.get("encode_s", 0.0) \
                    + rec.get("pack_s", 0.0)
                out["h2d_bytes"] += rec.get("h2d_bytes", 0)
                out["d2h_bytes"] += rec.get("d2h_bytes", 0)
                out["donated_bytes"] += rec.get("donated_bytes", 0)
                out["real_rows"] += rec.get("real", 0)
                out["padded_rows"] += rec.get("pad", 0) or rec.get(
                    "real", 0)
                if rec.get("overlap_s") is not None:
                    # pipeline overlap: lazy cycles only (mirrors
                    # DevProfiler.summary's overlap_share definition)
                    out["overlap_s"] += rec["overlap_s"]
                    out["overlap_block_s"] += rec.get("block_s", 0.0)
                    out["overlapped_cycles"] += 1
    phase_total = out["block_s"] + out["dispatch_s"] + out["encode_s"]
    out["device_wait_share"] = round(
        out["block_s"] / phase_total, 4) if phase_total > 0 else 0.0
    ov_window = out["overlap_s"] + out["overlap_block_s"]
    out["overlap_share"] = round(
        out["overlap_s"] / ov_window, 4) if ov_window > 0 else 0.0
    out["pad_waste_pct"] = round(
        100.0 * (1.0 - out["real_rows"] / out["padded_rows"]), 2) \
        if out["padded_rows"] else 0.0
    return out


# ---------------------------------------------------------------------------
# rendering


def scale_ab_flags(rounds: List[dict]) -> List[dict]:
    """The scale10x row family's own checks — throughput trend alone
    can't judge it. Each committed Scale10x row carries a same-scale
    partitioned-vs-single A/B, hard invariants, and the conflict
    chaos cell's verdict; flag the round when any of them fails:

    - ``ab.sharding_pays`` false (partitioned arm slower than the
      single-partition arm: the sharded fabric stopped paying for
      itself — a partition-layer regression even if the headline value
      still looks fine);
    - nonzero ``invariants`` (lost pods / double-binds);
    - a conflict cell that either broke an invariant or never
      conflicted (``ok`` false — a cell with zero conflicts proved
      nothing about the resolution path)."""
    flags: List[dict] = []
    for rnd in rounds:
        for row in rnd["rows"]:
            if "Scale10x" not in str(row.get("metric", "")) \
                    or "error" in row:
                continue
            problems = []
            ab = row.get("ab") or {}
            if ab and not ab.get("sharding_pays", True):
                problems.append(
                    f"partitioned {ab.get('partitioned_pods_per_sec')} "
                    f"< single-partition "
                    f"{ab.get('single_partition_pods_per_sec')} pods/s")
            inv = row.get("invariants") or {}
            for key in ("lost_pods", "double_binds"):
                if inv.get(key):
                    problems.append(f"{key}={inv[key]}")
            cell = row.get("conflict_cell") or {}
            if cell and not cell.get("ok", True):
                problems.append(
                    f"conflict cell failed (conflicts="
                    f"{cell.get('conflicts_total')}, lost="
                    f"{cell.get('lost_pods')}, double="
                    f"{cell.get('double_binds')})")
            if problems:
                flags.append({
                    "metric": row["metric"],
                    "round": rnd["round"],
                    "value": float(row.get("value", 0.0)),
                    "problems": problems,
                })
    return flags


def devscale_flags(rounds: List[dict]) -> List[dict]:
    """The devscale row family's own checks — a devices×throughput row
    can't be judged by its throughput trend alone. Flag the round when:

    - the solve fails its scaling bar: speedup at 4 devices < 1.5× vs
      the 1-device arm (the row's acceptance criterion), or — on REAL
      hardware rows only (``virtual_devices`` false/absent) — scaling
      efficiency (speedup ÷ devices) at 4 devices below 0.6: the mesh
      is mostly burning collective latency, a sharding-layer regression
      even when absolute throughput still looks fine. Virtual-device
      rows are exempt from the efficiency gate by construction: the
      forced host devices share silicon AND the 1-device baseline is
      intra-op multithreaded, so their efficiency understates any real
      mesh;
    - the donation A/B stopped paying: per-cycle h2d bytes or
      device-wait share NOT strictly lower with donation on — either
      the donated buffers regressed to real uploads or the transfer
      accounting started counting resident planes again (the metric-
      lies case the donated-bytes ledger exists to prevent)."""
    flags: List[dict] = []
    for rnd in rounds:
        for row in rnd["rows"]:
            if "devscale" not in str(row.get("metric", "")) \
                    or "error" in row:
                continue
            problems = []
            speedup = (row.get("solve_speedup_vs_1dev") or {}).get("4")
            if speedup is not None and speedup < 1.5:
                problems.append(
                    f"4-device solve speedup {speedup} < 1.5x")
            eff = row.get("scaling_efficiency_4dev")
            if eff is not None and eff < 0.6 \
                    and not row.get("virtual_devices"):
                problems.append(
                    f"scaling efficiency {eff} < 0.6 at 4 devices")
            ab = row.get("donation_ab") or {}
            if ab and not ab.get("donation_pays", True):
                on = ab.get("on") or {}
                off = ab.get("off") or {}
                problems.append(
                    "donation A/B not paying (h2d/cycle "
                    f"on={on.get('h2d_bytes_per_cycle')} "
                    f"off={off.get('h2d_bytes_per_cycle')}, d2h/cycle "
                    f"on={on.get('d2h_bytes_per_cycle')} "
                    f"off={off.get('d2h_bytes_per_cycle')}, wait share "
                    f"on={on.get('device_wait_share')} "
                    f"off={off.get('device_wait_share')})")
            if problems:
                flags.append({
                    "metric": row["metric"],
                    "round": rnd["round"],
                    "value": float(row.get("value", 0.0)),
                    "problems": problems,
                })
    return flags


def replay_flags(rounds: List[dict]) -> List[dict]:
    """The ``replay_*`` family's own checks (ISSUE 13 satellite):
    throughput trend alone cannot judge an open-loop trace-replay row.
    Flag the round when:

    - the row LOST pods (``lost_pods`` > 0, or short-injected — the
      zero-lost invariant is the suite's hardest bar);
    - any family invariant failed (``invariants_ok`` false: gang
      atomicity, priority inversion at quiesce, serve-latency budget);
    - the row's gated SLO verdicts went red (``slo_verdicts_ok``
      false — the family-exempt SLOs are already excluded row-side);
    - the gang family's adjacency A/B stopped paying
      (``adjacency_ab.scored_beats_blind`` false: MeshLocality scoring
      no longer beats the adjacency-blind arm).

    All gate ``--strict``."""
    flags: List[dict] = []
    for rnd in rounds:
        for row in rnd["rows"]:
            if not str(row.get("metric", "")).startswith("replay_") \
                    or "error" in row:
                continue
            problems = []
            if row.get("lost_pods"):
                problems.append(f"lost_pods={row['lost_pods']}")
            if row.get("invariants_ok") is False:
                bad = [k for k, v in
                       (row.get("invariants") or {}).items() if not v]
                problems.append(
                    "invariants failed: " + (", ".join(bad) or "?"))
            if row.get("slo_verdicts_ok") is False:
                slo = (row.get("freshness") or {}).get("slo") or {}
                bad = [n for n, v in slo.items() if v != "ok"
                       and n in (row.get("slo_gated") or slo)]
                problems.append(
                    "slo violated: " + (", ".join(sorted(bad)) or "?"))
            ab = row.get("adjacency_ab") or {}
            if ab and not ab.get("scored_beats_blind", True):
                problems.append(
                    f"adjacency A/B not paying (scored="
                    f"{ab.get('scored_mean_gang_adjacency')} vs blind="
                    f"{ab.get('blind_mean_gang_adjacency')})")
            if problems:
                flags.append({
                    "metric": row["metric"],
                    "round": rnd["round"],
                    "value": float(row.get("value", 0.0)),
                    "problems": problems,
                })
    return flags


def sustained_flags(rounds: List[dict]) -> List[dict]:
    """The ``sustained_arrival`` family's own checks (ISSUE 14
    satellite): the streaming scheduler's open-loop row cannot be
    judged by throughput — the offered rate pins it. Flag the round
    when:

    - p99 arrival→bind exceeds the 500 ms budget (the pipeline's
      latency acceptance bar — the barrier quantized p99 at
      whole-cycle time, and this is the number that proves it's gone);
    - the row LOST pods (``lost_pods`` > 0 or short-injected — the
      replay engine's hardest invariant);
    - the snapshot-staleness SLO verdict went red (a deeper in-flight
      window must never mean solving stale truth);
    - the pipeline stopped overlapping (``telemetry.overlap_share``
      == 0 on a row whose telemetry is present: the streaming loop
      silently degenerated back to the barrier).

    All gate ``--strict``."""
    flags: List[dict] = []
    for rnd in rounds:
        for row in rnd["rows"]:
            if not str(row.get("metric", "")).startswith(
                    "sustained_arrival") or "error" in row:
                continue
            problems = []
            p99 = row.get("p99_arrival_to_bind_ms")
            if p99 is not None and p99 > 500:
                problems.append(
                    f"p99 arrival→bind {p99}ms > 500ms budget")
            if row.get("lost_pods"):
                problems.append(f"lost_pods={row['lost_pods']}")
            if row.get("invariants_ok") is False:
                bad = [k for k, v in
                       (row.get("invariants") or {}).items() if not v]
                problems.append(
                    "invariants failed: " + (", ".join(bad) or "?"))
            slo = (row.get("freshness") or {}).get("slo") or {}
            verdict = slo.get("snapshot_staleness")
            if verdict is not None and verdict != "ok":
                problems.append(
                    f"snapshot_staleness SLO {verdict}")
            tel = row.get("telemetry") or {}
            if tel and "overlap_share" in tel \
                    and not tel.get("overlap_share"):
                problems.append(
                    "overlap_share=0 (pipeline degenerated to the "
                    "barrier)")
            if problems:
                flags.append({
                    "metric": row["metric"],
                    "round": rnd["round"],
                    "value": float(row.get("value", 0.0)),
                    "problems": problems,
                })
    return flags


def hotspot_flags(rounds: List[dict]) -> List[dict]:
    """The ``hotspot_recovery`` family's own checks (ISSUE 15
    satellite): the elastic-control-plane row is a RATIO row — its
    trend says nothing unless the migrations underneath were clean.
    Flag the round when:

    - any watch event was lost across the migrations
      (``lost_watches`` > 0 — the cursor-preserving handoff's hardest
      bar: an informer's final state diverged from server truth);
    - any hard invariant failed (``invariants_ok`` false: lost or
      duplicated pods, relists of unmoved slices, RV regressions, or
      the rebalancer never acting at all);
    - the recovery ratio fell below 0.8 (the rebalanced arm failed to
      claw back ≥80% of the balanced fleet's throughput — the row's
      acceptance bar).

    All gate ``--strict``."""
    flags: List[dict] = []
    for rnd in rounds:
        for row in rnd["rows"]:
            if not str(row.get("metric", "")).startswith(
                    "hotspot_recovery") or "error" in row:
                continue
            problems = []
            if row.get("lost_watches"):
                problems.append(
                    f"lost_watches={row['lost_watches']} (handoff "
                    f"dropped or duplicated events)")
            if row.get("invariants_ok") is False:
                # count-valued invariants are bad when NONZERO;
                # rebalancer_acted is the one boolean (bad when False)
                bad = [k for k, v in
                       (row.get("invariants") or {}).items()
                       if (not v if k == "rebalancer_acted"
                           else bool(v))]
                problems.append(
                    "invariants failed: " + (", ".join(bad) or "?"))
            ratio = row.get("recovery_ratio", row.get("value"))
            if ratio is not None and float(ratio) < 0.8:
                problems.append(
                    f"recovery_ratio {float(ratio):.3f} < 0.8 "
                    f"(rebalancer failed to recover balanced "
                    f"throughput)")
            if problems:
                flags.append({
                    "metric": row["metric"],
                    "round": rnd["round"],
                    "value": float(row.get("value", 0.0)),
                    "problems": problems,
                })
    return flags


def upgrade_flags(rounds: List[dict]) -> List[dict]:
    """The ``upgrade_roll`` family's own checks (ISSUE 16 satellite):
    the rolling-upgrade row is a THROUGHPUT-UNDER-SURGERY row — its
    trend says nothing unless the fleet actually kept serving while
    every process restarted. Flag the round when:

    - any pod was lost across the roll (``lost_pods`` > 0 — injected,
      acked, then absent from both server truth and the bind stream);
    - any watch event was lost or duplicated (``lost_watch_events`` /
      ``duplicated_events`` > 0 — a CompositeCursor failed to carry a
      client across a restart seam exactly-once);
    - any slice whose partition did NOT move was relisted
      (``unmoved_relists`` > 0 — the seam leaked beyond the restarted
      process);
    - a partition's write-freeze window blew its drain budget
      (``frozen_ms_max`` > ``freeze_budget_ms`` — the roll should have
      aborted and rolled back instead);
    - p99 arrival→bind exceeded 500 ms during the roll (the row's
      latency acceptance bar under open-loop load);
    - any freshness SLO went red during the roll
      (``slo_verdicts_ok`` false);
    - the mixed-version wire guard broke (``codec_failures`` > 0 — a
      client's pinned codec version was refused or mis-negotiated
      across a seam);
    - the roll was not exactly-once (``rolled_exactly_once`` false:
      a process restarted twice or never) or any other hard invariant
      failed (``invariants_ok`` false).

    All gate ``--strict``."""
    flags: List[dict] = []
    for rnd in rounds:
        for row in rnd["rows"]:
            if not str(row.get("metric", "")).startswith(
                    "upgrade_roll") or "error" in row:
                continue
            problems = []
            if row.get("lost_pods"):
                problems.append(
                    f"lost_pods={row['lost_pods']} (injected pods "
                    f"vanished across the roll)")
            if row.get("lost_watch_events"):
                problems.append(
                    f"lost_watch_events={row['lost_watch_events']} "
                    f"(informer diverged from server truth)")
            if row.get("duplicated_events"):
                problems.append(
                    f"duplicated_events={row['duplicated_events']} "
                    f"(a seam replayed events already delivered)")
            if row.get("unmoved_relists"):
                problems.append(
                    f"unmoved_relists={row['unmoved_relists']} "
                    f"(restart seam relisted a slice that never "
                    f"moved)")
            frozen = row.get("frozen_ms_max")
            budget = row.get("freeze_budget_ms")
            if (frozen is not None and budget is not None
                    and float(frozen) > float(budget)):
                problems.append(
                    f"frozen_ms_max {float(frozen):.1f} > budget "
                    f"{float(budget):.0f}ms (drain overran; should "
                    f"have aborted and rolled back)")
            p99 = row.get("p99_arrival_to_bind_ms")
            if p99 is not None and float(p99) > 500.0:
                problems.append(
                    f"p99_arrival_to_bind {float(p99):.0f}ms > 500ms "
                    f"under open-loop load during the roll")
            if row.get("slo_verdicts_ok") is False:
                problems.append(
                    "freshness SLO went red during the roll")
            if row.get("codec_failures"):
                problems.append(
                    f"codec_failures={row['codec_failures']} "
                    f"(mixed-version wire guard refused a client)")
            if row.get("rolled_exactly_once") is False:
                problems.append(
                    "roll not exactly-once (a process restarted "
                    "twice or never)")
            if row.get("invariants_ok") is False:
                why = (row.get("invariants") or {}).get("failed", "?")
                problems.append(f"invariants failed: {why}")
            if problems:
                flags.append({
                    "metric": row["metric"],
                    "round": rnd["round"],
                    "value": float(row.get("value", 0.0)),
                    "problems": problems,
                })
    return flags


def critpath_flags(rounds: List[dict]) -> List[dict]:
    """The fleet-tracing family's own checks (ISSUE 17 satellite): a
    bench row that carries a ``critical_path`` sub-object claims its
    latency is ATTRIBUTED — phase shares over the sampled pods'
    stitched cross-process span trees. Flag the round when:

    - ``unattributed_share`` > 0.05 (more than 5% of the summed
      in-flight windows has no covering phase span — the trace has a
      hole, so the phase shares cannot be trusted);
    - ``fully_attributed`` < 0.95 (fewer than 95% of sampled pods are
      individually ≤5% unattributed — the aggregate hides broken pods);
    - ``max_skew_ms`` exceeds ``skew_bound_ms`` (a scrape's half-RTT
      clock-offset bound was worse than the merge contract allows —
      cross-process orderings in the trace are not trustworthy);
    - a row that should carry a fleet trace lacks one: within rounds
      where at least one row DOES carry ``critical_path`` (tracing-era
      rounds — earlier committed artifacts predate the layer and stay
      green), a headline row measured with the tracer on
      (``trace_sample_rate`` > 0) or an ``upgrade_roll`` row without
      the sub-object means the collection silently broke.

    All gate ``--strict``."""
    flags: List[dict] = []
    for rnd in rounds:
        if not any("critical_path" in row for row in rnd["rows"]):
            continue
        for row in rnd["rows"]:
            if "error" in row:
                continue
            metric = str(row.get("metric", ""))
            cp = row.get("critical_path")
            problems = []
            if cp is None:
                should_carry = (
                    float(row.get("trace_sample_rate", 0.0) or 0.0) > 0
                    or metric.startswith("upgrade_roll"))
                if should_carry:
                    problems.append(
                        "row ran with tracing on but carries no "
                        "critical_path (fleet-trace collection "
                        "silently broke)")
            else:
                unatt = float(cp.get("unattributed_share", 0.0))
                if unatt > 0.05:
                    problems.append(
                        f"unattributed_share {unatt:.3f} > 0.05 "
                        f"(trace hole — phase shares untrustworthy)")
                fully = cp.get("fully_attributed")
                if fully is not None and float(fully) < 0.95:
                    problems.append(
                        f"fully_attributed {float(fully):.3f} < 0.95 "
                        f"(aggregate hides per-pod trace holes)")
                skew = float(cp.get("max_skew_ms", 0.0))
                bound = float(cp.get("skew_bound_ms", 50.0))
                if skew > bound:
                    problems.append(
                        f"max_skew_ms {skew:.3f} > bound {bound:.1f} "
                        f"(cross-process ordering not trustworthy)")
            if problems:
                flags.append({
                    "metric": metric,
                    "round": rnd["round"],
                    "value": float(row.get("value", 0.0) or 0.0),
                    "problems": problems,
                })
    return flags


def federation_flags(rounds: List[dict]) -> List[dict]:
    """The ``federation_*`` family's own checks (ISSUE 18 satellite):
    the federation rows are ROBUSTNESS-UNDER-PARTITION rows — a
    cross-cluster placement tier only earns its keep if losing a whole
    cluster loses zero pods and saturating one cluster stays invisible
    to its tenants. Flag the round when:

    - any pod was lost fleet-wide (``lost_pods`` > 0 — injected, acked
      by some cell, then absent from every survivor's truth);
    - a gang was split across clusters (``gang_splits`` > 0 — gangs
      place atomically or not at all; a cross-cluster split deadlocks
      the workload);
    - a SURVIVOR cell relisted (``survivor_relists`` > 0 — the
      cluster-loss seam leaked beyond the dead cell);
    - any per-cluster freshness/latency SLO went red
      (``per_cluster_slo_ok`` false — spillover must keep the
      saturated cell's own tenants green);
    - a cluster was failed over but fewer than 80% of its orphaned
      pods re-bound within the recovery budget (``recovery_ratio``
      < 0.8 with ``failovers`` > 0);
    - a spillover row spilled nothing (``spilled`` == 0 on a
      ``federation_spill`` row — the saturation penalty never fired,
      so the row measured a plain single-cluster run);
    - any fleet freshness SLO went red (``slo_verdicts_ok`` false) or
      any other hard invariant failed (``invariants_ok`` false).

    All gate ``--strict``."""
    flags: List[dict] = []
    for rnd in rounds:
        for row in rnd["rows"]:
            metric = str(row.get("metric", ""))
            if not metric.startswith("federation_") or "error" in row:
                continue
            problems = []
            if row.get("lost_pods"):
                problems.append(
                    f"lost_pods={row['lost_pods']} (pods vanished "
                    f"fleet-wide across the cluster loss)")
            if row.get("gang_splits"):
                problems.append(
                    f"gang_splits={row['gang_splits']} (a gang was "
                    f"split across clusters — placement must be "
                    f"atomic)")
            if row.get("survivor_relists"):
                problems.append(
                    f"survivor_relists={row['survivor_relists']} "
                    f"(cluster-loss seam leaked a relist into a "
                    f"surviving cell)")
            if row.get("per_cluster_slo_ok") is False:
                problems.append(
                    "a per-cluster SLO went red (spillover leaked "
                    "onto the saturated cell's own tenants)")
            ratio = row.get("recovery_ratio")
            if (row.get("failovers") and ratio is not None
                    and float(ratio) < 0.8):
                problems.append(
                    f"recovery_ratio {float(ratio):.2f} < 0.8 "
                    f"(failover re-placed too few orphans within "
                    f"the recovery budget)")
            if (metric.startswith("federation_spill")
                    and row.get("spilled") == 0):
                problems.append(
                    "spilled=0 on a spillover row (saturation "
                    "penalty never fired — row measured nothing)")
            if row.get("slo_verdicts_ok") is False:
                problems.append(
                    "fleet freshness SLO went red during the storm")
            if row.get("invariants_ok") is False:
                why = (row.get("invariants") or {}).get("failed", "?")
                problems.append(f"invariants failed: {why}")
            if problems:
                flags.append({
                    "metric": metric,
                    "round": rnd["round"],
                    "value": float(row.get("value", 0.0)),
                    "problems": problems,
                })
    return flags


def readtier_flags(rounds: List[dict]) -> List[dict]:
    """The ``watchherd*`` family's own checks (ISSUE 19 satellite):
    read-tier rows are LOSS-AND-STALENESS rows — replica-served
    watches only earn their keep if every informer converges to the
    owner's truth with zero lost or duplicated events, replicas stay
    inside their lag budget, and the replicated fan-out actually
    scales. Flag the round when:

    - an arm row (``watchherd[...]``) lost events (``lost_events`` or
      ``unconverged_informers`` > 0 — an informer's steady state
      diverged from the owner's truth at quiesce), re-applied a
      duplicate (``dup_suppressed`` > 0 on the happy path), relisted
      (``relists`` > 0 — a healthy tier never breaks a watch), never
      routed a single read through a replica while replicas were
      advertised (``replica_reads`` < 1 with ``replicas`` > 0),
      blew the replication-lag budget
      (``replication_lag_p99_ms`` > ``lag_budget_ms``), went red on
      the freshness SLO, or failed any hard invariant;
    - the scaling row (``watchherd_scaling[...]``) shows fan-out per
      owner CPU-second below the committed floor (``read_scaling_x``
      < ``read_scaling_floor_x``), the write path regressing against
      the replicas-off arm (``write_flat_ok`` false), or the
      differential arms disagreeing on final state
      (``differential_match`` false — replicas changed WHAT was
      stored, not just who served it);
    - a chaos cell row (``watchherd_cell[...]``) failed its scenario
      judgement (``ok``/``invariants_ok`` false), lost events, or
      leaked relists beyond the faulted replica
      (``relists_beyond_faulted`` > 0).

    All gate ``--strict``."""
    flags: List[dict] = []
    for rnd in rounds:
        for row in rnd["rows"]:
            metric = str(row.get("metric", ""))
            if not metric.startswith(("watchherd[", "watchherd_scaling[",
                                      "watchherd_cell[")) \
                    or "error" in row:
                continue
            problems = []
            if metric.startswith("watchherd["):
                if row.get("lost_events"):
                    problems.append(
                        f"lost_events={row['lost_events']} (informer "
                        f"steady state diverged from owner truth)")
                if row.get("unconverged_informers"):
                    problems.append(
                        f"unconverged_informers="
                        f"{row['unconverged_informers']} (herd never "
                        f"reached the owner's state hash)")
                if row.get("dup_suppressed"):
                    problems.append(
                        f"dup_suppressed={row['dup_suppressed']} "
                        f"(duplicate frames on the happy path)")
                if row.get("relists"):
                    problems.append(
                        f"relists={row['relists']} (a healthy read "
                        f"tier never breaks a watch)")
                if (row.get("replicas") and
                        not row.get("replica_reads")):
                    problems.append(
                        "replica_reads=0 with replicas advertised "
                        "(reads never routed through the read tier)")
                lag = row.get("replication_lag_p99_ms")
                budget = row.get("lag_budget_ms")
                if (lag is not None and budget
                        and float(lag) > float(budget)):
                    problems.append(
                        f"replication lag p99 {float(lag):.1f}ms over "
                        f"the {float(budget):.0f}ms budget")
                slo = (row.get("freshness") or {}).get("slo") or {}
                if any(v == "violated" for v in slo.values()):
                    red = [k for k, v in slo.items() if v == "violated"]
                    problems.append(
                        f"freshness SLO red: {', '.join(red)}")
            elif metric.startswith("watchherd_scaling["):
                floor = float(row.get("read_scaling_floor_x") or 1.5)
                sx = row.get("read_scaling_x")
                if sx is not None and float(sx) < floor:
                    problems.append(
                        f"read scaling {float(sx):.2f}x < {floor:.1f}x "
                        f"floor (fan-out per owner CPU-second)")
                if row.get("write_flat_ok") is False:
                    problems.append(
                        f"write throughput regressed vs the "
                        f"replicas-off arm "
                        f"(ratio {row.get('write_ratio')})")
                if row.get("differential_match") is False:
                    problems.append(
                        "differential arms disagree on final state "
                        "(replicas changed what was stored)")
            else:  # watchherd_cell[...]
                if row.get("ok") is False:
                    problems.append(
                        f"cell failed: {row.get('failure') or '?'}")
                if row.get("lost_events"):
                    problems.append(
                        f"lost_events={row['lost_events']} across the "
                        f"fault")
                if row.get("relists_beyond_faulted"):
                    problems.append(
                        f"relists_beyond_faulted="
                        f"{row['relists_beyond_faulted']} (fault seam "
                        f"leaked relists past the faulted replica)")
            if row.get("invariants_ok") is False:
                why = row.get("invariants") or row.get("failure") or "?"
                problems.append(f"invariants failed: {why}")
            if problems:
                flags.append({
                    "metric": metric,
                    "round": rnd["round"],
                    "value": float(row.get("value", 0.0)),
                    "problems": problems,
                })
    return flags


def mirror_flags(rounds: List[dict]) -> List[dict]:
    """Device-mirror rows (``bench.py --config mirrorab`` and the
    chaos-matrix mirror suite): the mirror-on sustained arm must keep
    its encode share near zero (the stage the mirror exists to kill),
    never reseed unexpectedly, and stay strictly below the committed
    donation-row per-cycle h2d; the A/B row must show the on arm at or
    below the off arm's per-cycle h2d with bit-identical placements;
    chaos cells must hold the differential across faults."""
    flags = []
    for rnd in rounds:
        for row in rnd["rows"]:
            metric = str(row.get("metric", ""))
            if not metric.startswith(("mirror_sustained[", "mirror_ab[",
                                      "mirror_cell[")) \
                    or "error" in row:
                continue
            problems = []
            if metric.startswith("mirror_sustained["):
                mirror = row.get("mirror") or {}
                on_arm = row.get("mirror_arm") == "on"
                if on_arm:
                    share = row.get("encode_share")
                    budget = float(
                        row.get("encode_share_budget") or 0.05)
                    if share is not None and float(share) >= budget:
                        problems.append(
                            f"encode share {float(share):.4f} >= "
                            f"{budget:g} budget on a mirror-on row "
                            f"(the resident planes should have killed "
                            f"the encode stage)")
                    allowed = int(row.get("reseeds_allowed") or 0)
                    if int(mirror.get("reseeds") or 0) > allowed:
                        problems.append(
                            f"reseeds={mirror['reseeds']} > "
                            f"{allowed} allowed (journal gaps or "
                            f"inexpressible deltas forced full host "
                            f"encodes mid-run)")
                    h2d = row.get("h2d_per_cycle_bytes")
                    h2d_budget = row.get("h2d_per_cycle_budget_bytes")
                    if (h2d is not None and h2d_budget
                            and float(h2d) >= float(h2d_budget)):
                        problems.append(
                            f"per-cycle h2d {float(h2d):,.0f}B >= the "
                            f"committed donation-row budget "
                            f"{float(h2d_budget):,.0f}B")
                if row.get("lost_pods"):
                    problems.append(
                        f"lost_pods={row['lost_pods']} (arrivals "
                        f"never bound)")
                p99 = row.get("p99_arrival_to_bind_ms")
                p99_budget = row.get("p99_budget_ms")
                if (p99 is not None and p99_budget
                        and float(p99) > float(p99_budget)):
                    problems.append(
                        f"arrival→bind p99 {float(p99):.0f}ms over "
                        f"the {float(p99_budget):.0f}ms SLO")
            elif metric.startswith("mirror_ab["):
                if row.get("differential_match") is False:
                    problems.append(
                        "differential arms disagree on final "
                        "placements (the mirror changed what was "
                        "bound)")
                on_h2d = row.get("h2d_per_cycle_on_bytes")
                off_h2d = row.get("h2d_per_cycle_off_bytes")
                # 10% headroom: per-cycle h2d jitters with batch
                # splits even over identical traces — the flag is for
                # scatter triples costing MORE than the encode they
                # replaced, not for cycle-count noise
                if (on_h2d is not None and off_h2d is not None
                        and float(on_h2d) > 1.10 * float(off_h2d)):
                    problems.append(
                        f"mirror-on per-cycle h2d "
                        f"{float(on_h2d):,.0f}B above the off arm's "
                        f"{float(off_h2d):,.0f}B (scatter triples "
                        f"cost more than the encode they replaced)")
            else:  # mirror_cell[...]
                if row.get("ok") is False:
                    problems.append(
                        f"cell failed: {row.get('failure') or '?'}")
                if row.get("differential_match") is False:
                    problems.append(
                        "differential arms disagree across the fault")
                if row.get("lost_pods"):
                    problems.append(
                        f"lost_pods={row['lost_pods']} across the "
                        f"fault")
            if row.get("invariants_ok") is False:
                why = row.get("invariants") or row.get("failure") or "?"
                problems.append(f"invariants failed: {why}")
            if problems:
                flags.append({
                    "metric": metric,
                    "round": rnd["round"],
                    "value": float(row.get("value", 0.0)),
                    "problems": problems,
                })
    return flags


def _short_metric(metric: str) -> str:
    m = re.match(r"(\w+)\[([^\]]*)\]", metric)
    return m.group(2) if m else metric


def render(series: Dict[str, List[dict]], flags: List[dict],
           band_floor: float = DEFAULT_NOISE_BAND) -> str:
    lines: List[str] = []
    open_flags = open_regressions(flags)
    recovered = [f for f in flags if "recovered_round" in f]
    flagged = {(f["metric"], f["round"]) for f in open_flags}
    for metric in sorted(series):
        points = series[metric]
        band = noise_band(points, floor=band_floor)
        lines.append(f"{_short_metric(metric)}  "
                     f"(noise band ±{band * 100:.0f}%)")
        lines.append(f"  {'round':>5} {'pods/s':>10} {'p99 ms':>8} "
                     f"{'Δ vs prior':>10}  flag")
        prev = None
        for p in points:
            delta = ""
            if prev and prev > 0:
                delta = f"{100.0 * (p['value'] - prev) / prev:+.1f}%"
            mark = "REGRESSION" if (metric, p["round"]) in flagged else ""
            p99 = f"{p['p99_ms']:.0f}" if p.get("p99_ms") is not None \
                else "-"
            lines.append(f"  r{p['round']:>4} {p['value']:>10.1f} "
                         f"{p99:>8} {delta:>10}  {mark}")
            prev = p["value"]
        lines.append("")
    if open_flags:
        lines.append("flagged regressions:")
        for f in open_flags:
            lines.append(
                f"  r{f['round']} {_short_metric(f['metric'])}: "
                f"{f['value']:.1f} vs baseline {f['baseline']:.1f} "
                f"({f['delta_pct']}%, band ±{f['band_pct']}%) — "
                f"{f['attribution']}")
    else:
        lines.append("no out-of-band regressions "
                     f"(band floor ±{band_floor * 100:.0f}%)")
    if recovered:
        lines.append("recovered regressions (back inside the band, "
                     "no longer gating):")
        for f in recovered:
            lines.append(
                f"  r{f['round']} {_short_metric(f['metric'])}: "
                f"{f['value']:.1f} ({f['delta_pct']}%) — recovered "
                f"in r{f['recovered_round']}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=_REPO_ROOT,
                    help="directory holding BENCH_r*.json")
    ap.add_argument("--band", type=float, default=DEFAULT_NOISE_BAND,
                    help="noise-band floor as a fraction (default 0.30)")
    ap.add_argument("--telemetry", default=None,
                    help="KTPU_TELEMETRY dir of per-cycle JSONL to "
                         "summarize alongside the trend")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any regression is flagged")
    args = ap.parse_args(argv)

    rounds = load_rounds(args.dir)
    if not rounds:
        print(f"no BENCH_r*.json under {args.dir}", file=sys.stderr)
        return 2
    series = build_series(rounds)
    flags = detect_regressions(series, band_floor=args.band)
    open_flags = open_regressions(flags)
    scale_flags = scale_ab_flags(rounds)
    dev_flags = devscale_flags(rounds)
    rep_flags = replay_flags(rounds)
    sus_flags = sustained_flags(rounds)
    hot_flags = hotspot_flags(rounds)
    upg_flags = upgrade_flags(rounds)
    fed_flags = federation_flags(rounds)
    crit_flags = critpath_flags(rounds)
    rt_flags = readtier_flags(rounds)
    mir_flags = mirror_flags(rounds)
    telemetry = summarize_telemetry(args.telemetry) \
        if args.telemetry else None
    if args.json:
        print(json.dumps({
            "rounds": [r["round"] for r in rounds],
            "series": {
                m: [{k: v for k, v in p.items() if k != "diags"}
                    for p in pts]
                for m, pts in series.items()
            },
            "regressions": open_flags,
            "recovered": [f for f in flags
                          if "recovered_round" in f],
            "scale_flags": scale_flags,
            "devscale_flags": dev_flags,
            "replay_flags": rep_flags,
            "sustained_flags": sus_flags,
            "hotspot_flags": hot_flags,
            "upgrade_flags": upg_flags,
            "federation_flags": fed_flags,
            "critpath_flags": crit_flags,
            "readtier_flags": rt_flags,
            "mirror_flags": mir_flags,
            "telemetry": telemetry,
        }, indent=1))
    else:
        print(render(series, flags, band_floor=args.band))
        if scale_flags:
            print("\nscale10x A/B / invariant flags:")
            for f in scale_flags:
                print(f"  r{f['round']} {_short_metric(f['metric'])}: "
                      + "; ".join(f["problems"]))
        if dev_flags:
            print("\ndevscale scaling / donation flags:")
            for f in dev_flags:
                print(f"  r{f['round']} {_short_metric(f['metric'])}: "
                      + "; ".join(f["problems"]))
        if rep_flags:
            print("\nreplay SLO / invariant flags:")
            for f in rep_flags:
                print(f"  r{f['round']} {_short_metric(f['metric'])}: "
                      + "; ".join(f["problems"]))
        if sus_flags:
            print("\nsustained-arrival latency / pipeline flags:")
            for f in sus_flags:
                print(f"  r{f['round']} {_short_metric(f['metric'])}: "
                      + "; ".join(f["problems"]))
        if hot_flags:
            print("\nhotspot recovery / handoff flags:")
            for f in hot_flags:
                print(f"  r{f['round']} {_short_metric(f['metric'])}: "
                      + "; ".join(f["problems"]))
        if upg_flags:
            print("\nrolling-upgrade / version-skew flags:")
            for f in upg_flags:
                print(f"  r{f['round']} {_short_metric(f['metric'])}: "
                      + "; ".join(f["problems"]))
        if fed_flags:
            print("\nfederation placement / cluster-loss flags:")
            for f in fed_flags:
                print(f"  r{f['round']} {_short_metric(f['metric'])}: "
                      + "; ".join(f["problems"]))
        if crit_flags:
            print("\nfleet-trace critical-path flags:")
            for f in crit_flags:
                print(f"  r{f['round']} {_short_metric(f['metric'])}: "
                      + "; ".join(f["problems"]))
        if rt_flags:
            print("\nread-tier watch-herd flags:")
            for f in rt_flags:
                print(f"  r{f['round']} {_short_metric(f['metric'])}: "
                      + "; ".join(f["problems"]))
        if mir_flags:
            print("\ndevice-mirror flags:")
            for f in mir_flags:
                print(f"  r{f['round']} {_short_metric(f['metric'])}: "
                      + "; ".join(f["problems"]))
        if telemetry:
            print(f"\ntelemetry stream ({args.telemetry}): "
                  f"{telemetry['cycles']} cycles "
                  f"({telemetry['warming_cycles']} warming), "
                  f"{telemetry['compiles']} compiles, "
                  f"device-wait share {telemetry['device_wait_share']:.0%}, "
                  f"overlap share {telemetry['overlap_share']:.0%}, "
                  f"pad waste {telemetry['pad_waste_pct']:.1f}%")
    return 1 if (args.strict
                 and (open_flags or scale_flags or dev_flags
                      or rep_flags or sus_flags or hot_flags
                      or upg_flags or fed_flags
                      or crit_flags or rt_flags
                      or mir_flags)) else 0


if __name__ == "__main__":
    raise SystemExit(main())
