"""Flight-recorder dump analyzer.

Ingests a Chrome/Perfetto ``trace_event`` JSON dump (produced by
``/debug/trace``, a degraded-mode entry, or ``Tracer.dump``) and prints:

- a per-phase latency-breakdown table (count, total, p50, p99 per span
  name), and
- the top-N slowest pods (by end-to-end trace extent) with their span
  trees, indented by containment.

Usage::

    python tools/trace_report.py dump.json [--top 5]

Also invoked as a smoke check from the slow-marker bench-path test
(``tests/test_tracer.py``) so a dump-format regression fails fast, before
a postmortem needs it.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(len(sorted_vals) * q))]


def load_events(path: str) -> List[dict]:
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a trace_event dump "
                         "(no traceEvents array)")
    for ev in events:
        for field in ("ph", "ts", "pid", "tid"):
            if field not in ev:
                raise ValueError(
                    f"{path}: malformed event (missing {field!r}): {ev}")
    return events


def phase_table(events: List[dict]) -> str:
    durs: Dict[str, List[float]] = {}
    for ev in events:
        if ev["ph"] != "X":
            continue
        durs.setdefault(ev["name"], []).append(ev.get("dur", 0.0) / 1000.0)
    lines = [f"{'phase':<24}{'count':>8}{'total_ms':>12}"
             f"{'p50_ms':>10}{'p99_ms':>10}"]
    for name in sorted(durs):
        vals = sorted(durs[name])
        lines.append(
            f"{name:<24}{len(vals):>8}{sum(vals):>12.1f}"
            f"{_percentile(vals, 0.50):>10.2f}"
            f"{_percentile(vals, 0.99):>10.2f}")
    return "\n".join(lines)


def _pod_traces(events: List[dict]) -> Dict[str, List[dict]]:
    """trace id (pod uid) -> that pod's events, chronological."""
    by_trace: Dict[str, List[dict]] = {}
    for ev in events:
        trace = (ev.get("args") or {}).get("trace")
        if trace:
            by_trace.setdefault(trace, []).append(ev)
    for evs in by_trace.values():
        evs.sort(key=lambda e: e["ts"])
    return by_trace


def _span_tree(evs: List[dict]) -> List[str]:
    """Indent spans by time containment (instant events at their
    position). ``evs`` must be chronological."""
    out: List[str] = []
    open_spans: List[dict] = []   # stack of enclosing X spans
    for ev in evs:
        start = ev["ts"]
        while open_spans and \
                open_spans[-1]["ts"] + open_spans[-1].get("dur", 0) < start:
            open_spans.pop()
        indent = "  " * len(open_spans)
        if ev["ph"] == "X":
            dur_ms = ev.get("dur", 0.0) / 1000.0
            out.append(f"{indent}{ev['name']}  {dur_ms:.2f}ms")
            open_spans.append(ev)
        else:
            out.append(f"{indent}@ {ev['name']}")
    return out


def slowest_pods(events: List[dict], top: int = 5) -> str:
    by_trace = _pod_traces(events)
    extents = []
    for trace, evs in by_trace.items():
        t0 = min(e["ts"] for e in evs)
        t1 = max(e["ts"] + e.get("dur", 0.0) for e in evs)
        extents.append((t1 - t0, trace, evs))
    extents.sort(reverse=True)
    lines: List[str] = []
    for extent_us, trace, evs in extents[:top]:
        pod = next((e["args"].get("pod") for e in evs
                    if e.get("args", {}).get("pod")), "")
        node = next((e["args"].get("node") for e in evs
                     if e.get("args", {}).get("node")), "")
        head = f"pod {trace}"
        if pod:
            head += f" ({pod})"
        if node:
            head += f" -> {node}"
        lines.append(f"{head}  e2e {extent_us / 1000.0:.2f}ms")
        lines.extend("  " + ln for ln in _span_tree(evs))
    return "\n".join(lines) if lines else "(no pod-level traces in dump)"


def fleet_report(path: str, top: int = 5) -> str:
    """Cross-process view of a MERGED fleet dump (``TraceFederation.
    merged()``): per-process tracks with their clock-offset/skew
    corrections, then the critical-path attribution table — which
    phase owns the sampled pods' end-to-end latency, fleet-wide."""
    import os
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from kubernetes_tpu.observability.fleettrace import critical_path

    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a trace_event dump "
                         "(no traceEvents array)")
    instances = (doc.get("otherData") or {}).get("instances") or {}
    if not instances:
        raise ValueError(
            f"{path}: not a merged fleet dump (no otherData.instances) "
            "— use the plain single-process report instead")
    lines = [f"fleet trace: {path}",
             f"{len(events)} events across {len(instances)} processes",
             "",
             "== per-process tracks ==",
             f"{'instance':<20}{'events':>8}{'offset_ms':>12}"
             f"{'skew_ms':>10}"]
    for name in sorted(instances):
        meta = instances[name]
        n = sum(1 for e in events
                if (e.get("args") or {}).get("instance") == name)
        lines.append(
            f"{name:<20}{n:>8}"
            f"{meta.get('offset_s', 0.0) * 1000.0:>12.3f}"
            f"{meta.get('skew_ms', 0.0):>10.3f}")
    errors = (doc.get("otherData") or {}).get("scrape_errors") or []
    for err in errors:
        lines.append(f"  scrape error: {err}")
    cp = critical_path(doc, max_pods=top)
    lines += ["",
              "== critical-path attribution "
              f"({cp['pods']} sampled pods, "
              f"{cp['fully_attributed']:.0%} fully attributed) ==",
              f"{'phase':<12}{'share':>10}"]
    for phase, share in sorted(cp["phase_shares"].items(),
                               key=lambda kv: -kv[1]):
        lines.append(f"{phase:<12}{share:>10.1%}")
    lines.append(f"{'(unattrib.)':<12}"
                 f"{cp['unattributed_share']:>10.1%}")
    lines += ["",
              f"top phase: {cp['top'] or '(none)'} "
              f"({cp['top_share']:.1%}); "
              f"max skew {cp['max_skew_ms']:.3f}ms "
              f"(bound {cp['skew_bound_ms']:.1f}ms)"]
    if cp.get("seam_windows"):
        lines.append(f"seam windows overlapped: {cp['seam_windows']}")
    if cp.get("per_pod"):
        lines += ["", f"== top-{top} pods by in-flight window =="]
        shown = sorted(cp["per_pod"],
                       key=lambda p: -p.get("window_ms", 0.0))[:top]
        for p in shown:
            phases = " ".join(
                f"{k}={v:.1f}ms" for k, v in sorted(
                    p.get("phases_ms", {}).items(),
                    key=lambda kv: -kv[1]))
            inst = ",".join(p.get("instances", []))
            lines.append(
                f"pod {p['trace']}  window {p['window_ms']:.2f}ms  "
                f"top {p['top'] or '(none)'}  "
                f"unattributed {p['unattributed_share']:.1%}  "
                f"[{inst}]  {phases}")
    return "\n".join(lines)


def report(path: str, top: int = 5) -> str:
    events = load_events(path)
    spans = sum(1 for e in events if e["ph"] == "X")
    pods = len(_pod_traces(events))
    return "\n".join([
        f"flight-recorder dump: {path}",
        f"{len(events)} events, {spans} spans, {pods} pod traces",
        "",
        "== per-phase latency breakdown ==",
        phase_table(events),
        "",
        f"== top-{top} slowest pods ==",
        slowest_pods(events, top),
    ])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("dump", help="path to a flight-recorder JSON dump")
    ap.add_argument("--top", type=int, default=5,
                    help="how many slowest pods to show")
    ap.add_argument("--fleet", action="store_true",
                    help="treat the dump as a merged fleet trace "
                         "(TraceFederation.merged()) and render the "
                         "cross-process critical-path table")
    args = ap.parse_args(argv)
    try:
        if args.fleet:
            print(fleet_report(args.dump, top=args.top))
        else:
            print(report(args.dump, top=args.top))
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
