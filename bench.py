"""Benchmark entry point (driver-run on real TPU hardware).

Measures the headline metric from BASELINE.json — pods scheduled/sec at
5k nodes / 30k pending pods — on the TPU batch path, against the host
serial path measured on the same cluster (the stock-scheduler stand-in;
BASELINE.md: "absolute reference numbers must be measured, not cited").

Default (the driver invocation) prints one JSON line PER workload —
configs 1-5, then the REST-fabric row, then the headline LAST (the
driver records the final lines of stdout; the reference likewise emits
per-workload DataItems, scheduler_perf/util.go:101-129). The REST row
prints immediately before the headline ON PURPOSE: the driver
tail-captures stdout, and a row printed mid-run falls out of the
artifact (VERDICT r5 weak #1). Every BASELINE.md matrix row is
therefore traceable to the driver artifact (VERDICT r2 weak #2):
    {"metric": ..., "value": N, "unit": "pods/s", "vs_baseline": N}
The REST row also carries ``store_direct_pods_per_sec`` and
``fabric_overhead_ratio`` (REST/store-direct, same process, same
scale): the fabric's cost is a first-class bench number.

Options (all optional):
    --config {1..5|headline|rest}  run ONE workload instead of the matrix
    --all             the default matrix PLUS Preemption, Unschedulable,
                      Mixed, and PV families at bench scale
    --quick           small scale smoke (CI-sized)
    --skip-serial     reuse the last recorded serial baseline
    --sharded-cpu     multi-chip scaling shape on the 8-device virtual
                      CPU mesh (VERDICT r2 #4) — see bench_sharded.py
    --rest-qps N      per-client QPS for the REST row (default 5000,
                      the reference harness's client discipline;
                      0 = uncapped)

The ``rest`` row runs the headline workload through the REAL API
fabric (VERDICT r4 missing #1): apiserver process with WAL + RBAC +
admission, QPS-capped creator clients POSTing over REST, scheduler fed
by watch streams, binds through the Binding subresource. The
store-direct rows measure the scheduler alone (the reference's
framework-internal integration-test posture); the rest row measures
the deployable system.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from kubernetes_tpu.harness import make_workload

def run_workload(*args, **kwargs):
    """Lazy: the REST row's spawn children re-import this module and
    must not pull the jax-importing perf harness."""
    from kubernetes_tpu.harness import perf

    return perf.run_workload(*args, **kwargs)

# measured host-serial baselines (pods/s), updated by full runs
RECORDED_SERIAL_BASELINE = {
    # 5k nodes, python serial path; re-measured 2026-07-30 after the
    # round-2 host-path work (bulk admission + from_dict + GC tuning)
    "default": 61.7,
}

CONFIGS = {
    # BASELINE.json configs -> (workload, nodes, init_pods, measure_pods)
    "1": ("SchedulingBasic", 100, 0, 1000),
    "2": ("SchedulingBasic", 1000, 0, 10000),
    "3": ("TopologySpreading", 5000, 0, 30000),
    "4": ("SchedulingPodAntiAffinity", 5000, 1000, 30000),
    "5": ("GangScheduling", 5000, 0, 30000),
    "headline": ("SchedulingBasic", 5000, 0, 30000),
}

# the --all matrix: the five BASELINE configs plus the families VERDICT
# r1 called out as unmeasured (Preemption, Unschedulable, Mixed, PVs)
EXTRA_MATRIX = {
    # init exactly fills the cluster (5000 nodes x 4cpu, 3cpu fillers ->
    # one per node); every measured high-priority pod must preempt. More
    # init pods than fit would deadlock the init op's wait-for-scheduled.
    "preemption": ("Preemption", 5000, 5000, 5000),
    # preemptors carrying PVCs (victim eviction + volume feasibility in
    # one flow; reference performance-config.yaml:399)
    "preemptionpvs": ("PreemptionPVs", 5000, 5000, 5000),
    # 1000 impossible pods stay pending (skipWaitToCompletion) while the
    # measured pods schedule around them
    "unschedulable": ("Unschedulable", 5000, 1000, 10000),
    "mixed": ("MixedSchedulingBasePod", 5000, 1000, 30000),
    # the PV families ride the batch path since round 3 (bound-claim
    # masks + attach columns); all three recorded to show the breadth
    "csipvs": ("SchedulingCSIPVs", 1000, 0, 5000),
    "intreepvs": ("SchedulingInTreePVs", 1000, 0, 5000),
    "migratedpvs": ("SchedulingMigratedInTreePVs", 1000, 0, 5000),
    # shared/unbound-claim family (VERDICT r3 weak #7): non-CSI shared
    # claims batch via static masks, WFC claims via commit-time
    # binding, and since round 5 the CSI-shared slice batches too
    # (per-volume attach planes in solver state) — the whole family
    # rides the device path
    "sharedpvs": ("SchedulingSharedPVs", 1000, 0, 3000),
    # the 6 families VERDICT r4 called out as built-but-never-measured,
    # at the reference's OWN 5000Nodes scales
    # (performance-config.yaml:51,168,197,224,251,305)
    "secrets": ("SchedulingSecrets", 5000, 5000, 1000),
    "podaffinity": ("SchedulingPodAffinity", 5000, 5000, 1000),
    "prefpodaffinity": ("SchedulingPreferredPodAffinity", 5000, 5000, 1000),
    "prefantiaffinity": ("SchedulingPreferredPodAntiAffinity",
                         5000, 5000, 1000),
    "nodeaffinity": ("SchedulingNodeAffinity", 5000, 5000, 1000),
    "preftopospread": ("PreferredTopologySpreading", 5000, 5000, 2000),
}


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def matrix_row_order(include_extra: bool = False) -> list:
    """Emission order for the default matrix. The REST-fabric row comes
    SECOND-TO-LAST — after the config rows, immediately before the
    headline — so the driver's tail capture of stdout always contains
    it next to the headline (the round-5 artifact lost the REST row
    because it printed first and fell out of the tail). The
    noisy-tenant QoS row (multi-tenant overload through APF) rides
    right before the REST row. Guarded by
    tests/test_fastfabric.py::TestBenchRowOrder."""
    order = ["1", "2", "3", "4", "5"]
    if include_extra:
        order += sorted(EXTRA_MATRIX)
    return order + ["scale10x", "qos", "rest", "headline"]


_APF_REJECTED_SEEN = 0.0   # cumulative-counter baseline for the apf diag
                           # segment: each row reports only ITS rejections


def _diagnose(sched, bs) -> None:
    """Per-run solver diagnostics on stderr (kept permanently: when a
    row's p99 blows its budget, the root cause — a slow batch absorbing
    a rebuild/recompile, tunnel stall, chunk collapse — must be readable
    from the run's own log, not re-derived by a fresh profiling run).
    Phase breakdowns come from the flight-recorder tracer and the
    device profiler (the instrumentation layers feeding logs, /metrics,
    Perfetto dumps, the per-cycle telemetry stream and this line), and
    every segment is RENDERED by harness/diagfmt.py — one writer, one
    parser (tools/perf_report.py), no ad-hoc regexes."""
    try:
        from kubernetes_tpu.harness import diagfmt
        from kubernetes_tpu.observability import get_tracer

        tracer = get_tracer()
        segs = []
        if tracer.enabled:
            segs.extend(diagfmt.format_phases(tracer.phase_stats()))
        else:
            # tracer off (e.g. the A/B's off arm): the solver-segment
            # histogram still holds the breakdown — a blown p99 must be
            # explainable from this run's log either way
            segs.append("tracer=off")
            segs.extend(diagfmt.format_hist_segments(
                sched.metrics.batch_solve_duration))
        # e2e p99 + legacy bucket text, both rendered from the SAME
        # metrics-registry histogram /metrics exposes (interpolated
        # quantile; the diag line and the scrape cannot disagree)
        buckets = diagfmt.format_e2e(sched.metrics.e2e_scheduling_duration)
        sess = ""
        devprof_seg = ""
        mesh_seg = ""
        pipe_seg = ""
        mirror_seg = ""
        if bs is not None:
            sess = " " + diagfmt.format_session(
                bs.session, bs._chunk, bs.max_cycle_s, bs.pad_warms)
            from kubernetes_tpu.observability.devprof import get_devprof

            dp = get_devprof()
            summary = dp.summary() if dp.enabled else None
            if dp.enabled:
                if summary["cycles"] or summary["warm_compiles"]:
                    devprof_seg = " " + diagfmt.format_devprof(summary)
                # streaming-pipeline segment: stage depth + how much of
                # the in-flight device window host work hid (only when
                # the pipeline is on — the off arm prints nothing)
                pipe_seg = " " + diagfmt.format_pipeline(
                    bs.pipeline_info(summary))
            # device-mirror segment: watch deltas scattered into the
            # resident planes, their link cost, and the surviving
            # encode share (only when the session carries a mirror —
            # KTPU_MIRROR=off rows print nothing)
            if hasattr(bs, "mirror_info"):
                mirror_seg = " " + diagfmt.format_mirror(
                    bs.mirror_info(summary))
            # mesh segment, only when the row actually solved on the
            # sharded tier: mesh width, shard count, donation — the
            # provenance a devscale (or sharded-default REST) row's
            # diag needs to be attributable from the line alone
            mesh_seg = " " + diagfmt.format_mesh(bs.mesh_info())
        # node-churn segment, only when churn actually happened this
        # process (chaos_nodes harness / a churn-enabled run): the
        # eviction/stale-reject/rescue numbers explain a degraded row
        # the same way the session counters explain a slow one
        churn = ""
        from kubernetes_tpu.metrics.fabric_metrics import fabric_metrics

        fm = fabric_metrics()
        evictions = sum(v for _, _, v in fm.node_evictions_total.collect())
        stale = sum(
            v for _, _, v in fm.stale_binds_rejected_total.collect())
        if evictions or stale:
            p99 = fm.pod_rescue_seconds.quantile(0.99)
            churn = (f" churn[evictions={evictions:.0f} "
                     f"stale_rejected={stale:.0f} "
                     f"rescue_p99={p99 * 1000:.0f}ms]")
        # autoscaler segment, only when the elastic layer acted this
        # process (the autoscale row / an elastic chaos run): scale
        # events + time-to-capacity explain an elastic row's tail the
        # way the churn numbers explain a degraded one
        autoscale = ""
        from kubernetes_tpu.metrics.autoscaler_metrics import (
            autoscaler_metrics,
        )

        am = autoscaler_metrics()
        ups = sum(v for _, _, v in am.scaleups_total.collect())
        downs = sum(v for _, _, v in am.scaledowns_total.collect())
        if ups or downs:
            ttc = am.time_to_capacity_seconds.quantile(0.99)
            autoscale = (
                f" autoscaler[nodes_up={ups:.0f} nodes_down={downs:.0f} "
                f"pending={am.pending_unschedulable.get():.0f} "
                f"ttc_p99={ttc:.1f}s]")
        # APF segment, only when flow control actually rejected
        # something THIS ROW (REST rows mirror the server child's
        # /debug/apf totals into these counters): who got pushed back,
        # how long queues held requests, and how full each level ran.
        # The counters are cumulative and the metrics singleton outlives
        # the row, so the segment deltas against the previous row's
        # total and consumes the absorbed snapshot — a quiet row must
        # never re-print an earlier row's rejections as its own.
        global _APF_REJECTED_SEEN
        apf = ""
        from kubernetes_tpu.metrics.apf_metrics import apf_metrics

        apfm = apf_metrics()
        rejected_cum = sum(v for _, _, v
                           in apfm.rejected_requests_total.collect())
        rejected = rejected_cum - _APF_REJECTED_SEEN
        _APF_REJECTED_SEEN = rejected_cum
        snap, apfm.last_snapshot = apfm.last_snapshot, None
        if rejected:
            if snap:
                # remote-server row: queue waits and peak seats live in
                # the absorbed /debug/apf snapshot, not local series
                levels = snap.get("levels") or {}
                qwait_p99 = max(
                    (lv.get("queue_wait_p99_s", 0.0)
                     for lv in levels.values()), default=0.0)
                seats = " ".join(
                    f"{name}={lv.get('peak_executing_seats', 0)}"
                    f"/{lv.get('capacity', 0)}"
                    for name, lv in sorted(levels.items()))
            else:
                # in-process server: the live per-level series. Peak
                # seats come from the high-water gauge, NOT the current
                # gauge — by diag time the row's requests have drained
                # and "current" would report an idle level for a row
                # saturated enough to reject
                qwait_p99 = max(
                    (apfm.request_queue_wait_seconds.quantile(
                        0.99, lvl[0])
                     for _, lvl, _v
                     in apfm.request_concurrency_limit.collect()),
                    default=0.0)
                seats = " ".join(
                    f"{lvl[0]}="
                    f"{apfm.peak_executing_seats.get(lvl[0]):.0f}"
                    f"/{v:.0f}"
                    for _, lvl, v
                    in apfm.request_concurrency_limit.collect())
            apf = (f" apf[rejected={rejected:.0f} "
                   f"queue_wait_p99={qwait_p99 * 1000:.0f}ms "
                   f"peak_seats: {seats}]")
        # consume the peak high-water marks and the queue-wait series
        # whether or not the segment printed: each row's apf numbers
        # are ITS numbers, not process-lifetime accumulations (the
        # queue-wait clear only matters for an in-process apf server —
        # bench rows run the server in a child and absorb /debug/apf)
        for _, lbl, _v in apfm.peak_executing_seats.collect():
            apfm.peak_executing_seats.set(0.0, *lbl)
        apfm.request_queue_wait_seconds.clear()
        # SLO segment, only when an objective is violated THIS ROW
        # (mirrors the apf convention): the engine's window was reset
        # at row start by the harness, so the verdicts are the row's
        slo_seg = ""
        from kubernetes_tpu.observability.slo import get_slo_engine

        engine = get_slo_engine()
        if engine.enabled:
            slo_seg = diagfmt.format_slo(engine.evaluate())
        # fleet critical-path segment from THIS process's ring (the
        # full cross-process merge rides the row JSON; the diag line
        # carries the scheduler-side attribution so a blown p99 names
        # its phase from the log alone)
        crit_seg = ""
        if tracer.enabled:
            from kubernetes_tpu.harness.perf import collect_critical_path

            cp, _doc = collect_critical_path()
            crit_seg = diagfmt.format_critpath(cp)
        log(diagfmt.format_diag(
            segs + [sess.strip(), devprof_seg.strip(), pipe_seg.strip(),
                    mirror_seg.strip(), mesh_seg.strip(), churn.strip(),
                    autoscale.strip(), apf.strip(), slo_seg, crit_seg]
            + buckets))
    except Exception as e:  # noqa: BLE001 — diagnostics must never fail a row
        log(f"    diag failed: {e}")


def run_one(key: str, name: str, nodes: int, init_pods: int,
            measure_pods: int, serial_rate: float,
            repeat: int = 1) -> dict:
    """One workload row. ``repeat > 1`` runs the measured phase that
    many times and reports the MEDIAN — the shared TPU tunnel's
    contention swings single runs by ±30%, which is noise about the
    binary, not signal (all samples are carried in the JSON line)."""
    samples = []
    for r in range(repeat):
        ops = make_workload(name, nodes=nodes, init_pods=init_pods,
                            measure_pods=measure_pods)
        t0 = time.time()
        # 4096 measured within noise of 8192 on throughput (solve/commit
        # overlap hides the extra cycles) while halving the per-cycle p99
        # contribution — and the p99 budget is part of the headline metric
        batch = run_workload(f"{name}/batch", ops, use_batch=True,
                             max_batch=min(measure_pods, 4096),
                             wait_timeout=1200, progress=log,
                             result_hook=_diagnose)
        # --all runs many workloads in one process; the GC tuning used
        # for throughput defers collection, so reclaim the previous
        # session's device-resident arrays before the next compile
        import gc

        gc.collect()
        log(f"[{key}] batch run {r + 1}/{repeat}: "
            f"{batch.pods_per_second:.1f} pods/s "
            f"(wall {time.time() - t0:.1f}s, p99 latency "
            f"{batch.metrics.get('Perc99', 0):.0f}ms)")
        samples.append(batch)
    samples.sort(key=lambda b: b.pods_per_second)
    median = samples[len(samples) // 2]
    row = {
        "metric": f"pods_scheduled_per_sec[{name} {nodes}nodes/"
                  f"{measure_pods}pods, TPU batch path]",
        "value": round(median.pods_per_second, 1),
        "unit": "pods/s",
        "p99_latency_ms": round(median.metrics.get("Perc99", 0)),
        "vs_baseline": round(
            median.pods_per_second / serial_rate, 2
        ) if serial_rate > 0 else 0.0,
    }
    if repeat > 1:
        row["runs"] = [round(b.pods_per_second, 1) for b in samples]
    if median.telemetry:
        # the devprof per-cycle summary rides every row into the
        # driver-captured artifact: compile count, device-wait share,
        # pad waste, and the slowest cycle's phase attribution are
        # readable from the committed JSON without a re-run
        row["telemetry"] = median.telemetry
    if median.freshness:
        # the SLI layer's numbers (watch-delivery p99, max snapshot
        # staleness, SLO verdicts) ride the artifact the same way —
        # tools/slo_report.py renders the per-row verdict table from
        # exactly this sub-object
        row["freshness"] = median.freshness
    if median.critical_path:
        # fleet critical-path attribution (phase shares of the sampled
        # pods' end-to-end latency) — tools/trace_report.py --fleet and
        # perf_report's critpath_flags read exactly this sub-object
        row["critical_path"] = median.critical_path
    if key == "headline":
        # provenance for the trace-overhead tracking (--config traceab):
        # which sampling config this headline number was measured under
        from kubernetes_tpu.observability import get_tracer

        t = get_tracer()
        row["trace_sample_rate"] = t.sample_rate if t.enabled else 0.0
    return row


def run_rest_one(nodes: int, measure_pods: int, serial_rate: float,
                 qps: float, repeat: int = 3) -> dict:
    """The REST-fabric row: headline workload, every byte over HTTP.
    Median-of-repeat like the other rows (tunnel variance). Also runs
    the SAME workload store-direct in the SAME process (one run — the
    A/B's job is attribution, not its own precision) and reports the
    fabric-overhead ratio REST/store-direct as a first-class number:
    how much of the headline survives the deployable fabric."""
    from kubernetes_tpu.harness.rest_perf import run_workload_rest

    samples = []
    for r in range(repeat):
        t0 = time.time()
        res = run_workload_rest(
            "SchedulingBasic", nodes=nodes, measure_pods=measure_pods,
            max_batch=min(measure_pods, 4096),
            qps=qps if qps > 0 else None,
            wait_timeout=1200, progress=log, result_hook=_diagnose,
        )
        import gc

        gc.collect()
        log(f"[rest] run {r + 1}/{repeat}: "
            f"{res.pods_per_second:.1f} pods/s "
            f"(wall {time.time() - t0:.1f}s, p99 "
            f"{res.metrics.get('Perc99', 0):.0f}ms, server bound "
            f"{res.metrics.get('server_pods_bound')}, WAL entries "
            f"{res.metrics.get('wal_entries')})")
        samples.append(res)
    samples.sort(key=lambda b: b.pods_per_second)
    median = samples[len(samples) // 2]
    # store-direct arm of the A/B (same process, same scale): the
    # remaining gap REST/store-direct is fabric overhead by definition
    direct_rate = 0.0
    try:
        ops = make_workload("SchedulingBasic", nodes=nodes, init_pods=0,
                            measure_pods=measure_pods)
        direct = run_workload("SchedulingBasic/direct-ab", ops,
                              use_batch=True,
                              max_batch=min(measure_pods, 4096),
                              wait_timeout=1200, progress=log)
        direct_rate = direct.pods_per_second
        import gc

        gc.collect()
        log(f"[rest] store-direct A/B arm: {direct_rate:.1f} pods/s "
            f"(fabric overhead ratio "
            f"{median.pods_per_second / direct_rate:.3f})")
    except Exception as e:  # noqa: BLE001 — the REST row must survive
        log(f"[rest] store-direct A/B arm failed: {e}")
    row = {
        "metric": f"pods_scheduled_per_sec[SchedulingBasic {nodes}nodes/"
                  f"{measure_pods}pods, REST fabric "
                  f"(apiserver+WAL+watch, client QPS "
                  f"{int(qps) if qps > 0 else 'uncapped'})]",
        "value": round(median.pods_per_second, 1),
        "unit": "pods/s",
        "p99_latency_ms": round(median.metrics.get("Perc99", 0)),
        "vs_baseline": round(
            median.pods_per_second / serial_rate, 2
        ) if serial_rate > 0 else 0.0,
        "server_pods_bound": median.metrics.get("server_pods_bound"),
        "wal_entries": median.metrics.get("wal_entries"),
        "store_direct_pods_per_sec": round(direct_rate, 1),
        "fabric_overhead_ratio": round(
            median.pods_per_second / direct_rate, 3
        ) if direct_rate > 0 else 0.0,
    }
    if repeat > 1:
        row["runs"] = [round(b.pods_per_second, 1) for b in samples]
    if median.telemetry:
        row["telemetry"] = median.telemetry
    if median.freshness:
        row["freshness"] = median.freshness
        # which components' registries the federation merged for this
        # row (≥2 = the cross-process path measured real children)
        row["federation_instances"] = \
            median.metrics.get("federation_instances", [])
    if median.critical_path:
        row["critical_path"] = median.critical_path
    return row


def run_scale10x_one(serial_rate: float, qps: float,
                     quick: bool = False) -> dict:
    """The 10×-tier row (ROADMAP "50k-node / 500k-pod tier"): the
    partitioned control plane — P apiserver processes (one store
    partition each), kubemark hollow fleet, M concurrently-scheduling
    replicas — at ≥10× the headline scale, with a same-scale
    single-partition arm as the A/B (sharding must pay for itself) and
    the conflict chaos cell's verdict riding the row."""
    from kubernetes_tpu.harness.scale import run_scale10x_row

    if quick:
        row = run_scale10x_row(
            nodes=400, pods=2000, partitions=2, replicas=2,
            use_batch=True, max_batch=512,
            qps=qps if qps > 0 else None,
            node_cpu=16, wait_timeout=600, progress=log)
    else:
        row = run_scale10x_row(
            nodes=50_000, pods=500_000, partitions=4, replicas=2,
            use_batch=True, max_batch=1024,
            qps=qps if qps > 0 else None,
            node_cpu=32, wait_timeout=2400, progress=log)
    row["vs_baseline"] = round(
        row["value"] / serial_rate, 2) if serial_rate > 0 else 0.0
    return row


def run_qos_one(nodes: int, measure_pods: int, serial_rate: float,
                qps: float, tenants: int = 3,
                solo_baseline: dict = None) -> dict:
    """The noisy-tenant QoS row: the headline workload over REST while
    N aggressor tenants mount list storms, watch reconnect herds, and
    bulk-verb abuse — API Priority & Fairness must hold the victim's
    p99 within 2x its solo arm (the ratio is the row's acceptance
    verdict). In the default matrix the adjacent REST row IS the solo
    arm (identical configuration) and is passed as ``solo_baseline``;
    standalone ``--config qos`` measures its own."""
    from kubernetes_tpu.harness.qos import run_noisy_tenant_qos

    row = run_noisy_tenant_qos(
        nodes=nodes, measure_pods=measure_pods, tenants=tenants,
        qps=qps if qps > 0 else None,
        max_batch=min(measure_pods, 4096),
        wait_timeout=1200, progress=log, result_hook=_diagnose,
        solo_baseline=solo_baseline)
    row["vs_baseline"] = round(
        row["value"] / serial_rate, 2) if serial_rate > 0 else 0.0
    return row


def _layer_ab(tag: str, layer: str, set_enabled,
              nodes: int, measure_pods: int, repeat: int) -> dict:
    """Shared on/off A/B harness for an instrumentation layer's
    steady-state overhead (tracer, devprof — both tracked rows judge
    the same methodology, so it lives in ONE place). One unmeasured
    warmup run absorbs compile/allocator warm-state, then the arms
    INTERLEAVE with alternating pair order per round — a blocked
    on-then-off order would hand all the process warm-state (JIT
    cache, allocator) to the second mode and misattribute it as layer
    cost. Returns per-arm medians, the overhead %, and the max
    within-arm run-to-run spread (the noise band the overhead is
    judged against)."""
    import gc

    def one_run(mode: str):
        ops = make_workload("SchedulingBasic", nodes=nodes,
                            init_pods=0, measure_pods=measure_pods)
        res = run_workload(f"SchedulingBasic/{tag}-{mode}", ops,
                           use_batch=True,
                           max_batch=min(measure_pods, 4096),
                           wait_timeout=1200, progress=log)
        gc.collect()
        return res.pods_per_second

    samples = {"on": [], "off": []}
    one_run("warm")   # unmeasured: absorbs compile/allocator warmup
    for r in range(repeat):
        for mode in (("off", "on") if r % 2 == 0 else ("on", "off")):
            set_enabled(mode == "on")
            samples[mode].append(one_run(mode))
    rates = {}
    noise_pct = 0.0
    for mode, vals in samples.items():
        vals.sort()
        rates[mode] = vals[len(vals) // 2]
        if rates[mode] > 0:
            noise_pct = max(
                noise_pct, 100.0 * (vals[-1] - vals[0]) / rates[mode])
        log(f"[{tag}-ab] {layer} {mode}: {rates[mode]:.1f} pods/s "
            f"(runs {[round(v, 1) for v in vals]})")
    overhead_pct = 0.0
    if rates["off"] > 0:
        overhead_pct = 100.0 * (1.0 - rates["on"] / rates["off"])
    return {"rates": rates, "overhead_pct": overhead_pct,
            "noise_pct": noise_pct}


def run_trace_ab(nodes: int, measure_pods: int, repeat: int = 1) -> dict:
    """Tracer-on vs tracer-off headline A/B: the observability layer's
    steady-state overhead, tracked as a BENCH_* row across PRs (the
    <3% budget is an acceptance bar, so it must be measured, not
    assumed). Tracer-on uses the DEFAULT sampling config."""
    from kubernetes_tpu.observability import get_tracer
    from kubernetes_tpu.observability.tracer import DEFAULT_SAMPLE_RATE

    tracer = get_tracer()
    prev_enabled, prev_rate = tracer.enabled, tracer.sample_rate
    try:
        # the tracked row must measure the DEFAULT sampling config, not
        # whatever KTPU_TRACE_SAMPLE happens to be live — otherwise the
        # cross-PR overhead trend compares incomparable configurations
        tracer.configure(sample_rate=DEFAULT_SAMPLE_RATE)
        ab = _layer_ab("trace", "tracer",
                       lambda on: tracer.configure(enabled=on),
                       nodes, measure_pods, repeat)
    finally:
        tracer.configure(enabled=prev_enabled, sample_rate=prev_rate)
    return {
        "metric": f"trace_overhead_pct[SchedulingBasic {nodes}nodes/"
                  f"{measure_pods}pods, default sampling "
                  f"1/{round(1 / DEFAULT_SAMPLE_RATE)}]",
        "value": round(ab["overhead_pct"], 2),
        "unit": "%",
        "tracer_on_pods_per_sec": round(ab["rates"]["on"], 1),
        "tracer_off_pods_per_sec": round(ab["rates"]["off"], 1),
    }


def run_profile_ab(nodes: int, measure_pods: int, repeat: int = 1) -> dict:
    """Devprof-on vs devprof-off headline A/B (``--config profab``):
    the hot-path telemetry layer's steady-state overhead, measured the
    same way the tracer A/B measures its layer (the ≈0 bar is an
    acceptance criterion, so it is measured, not assumed). The row
    reports the overhead next to the run-to-run noise band so "within
    noise" is a number, not a claim."""
    from kubernetes_tpu.observability.devprof import get_devprof

    dp = get_devprof()
    prev_enabled = dp.enabled
    try:
        ab = _layer_ab("prof", "devprof",
                       lambda on: dp.configure(enabled=on),
                       nodes, measure_pods, repeat)
    finally:
        dp.configure(enabled=prev_enabled)
    return {
        "metric": f"devprof_overhead_pct[SchedulingBasic {nodes}nodes/"
                  f"{measure_pods}pods, telemetry on/off A/B]",
        "value": round(ab["overhead_pct"], 2),
        "unit": "%",
        "devprof_on_pods_per_sec": round(ab["rates"]["on"], 1),
        "devprof_off_pods_per_sec": round(ab["rates"]["off"], 1),
        # run-to-run spread within the arms: the bar the overhead is
        # judged against (overhead within the band = within noise);
        # null with a single run per arm — one sample has no spread to
        # judge against, and a 0% band would flag pure noise
        "noise_band_pct": round(ab["noise_pct"], 2),
        "within_noise": (abs(ab["overhead_pct"])
                         <= max(ab["noise_pct"], 1.0))
        if repeat > 1 else None,
    }


def run_freshness_ab(nodes: int, measure_pods: int,
                     repeat: int = 1) -> dict:
    """Freshness+SLO layer on/off headline A/B (``--config freshab``):
    event stamping, per-batch delivery/lag observation, per-cycle
    staleness, and the SLO engine's sampling, measured against the
    same interleaved-arms noise band the tracer and devprof layers are
    judged by."""
    from kubernetes_tpu.metrics.freshness_metrics import freshness_metrics
    from kubernetes_tpu.observability.slo import get_slo_engine

    fm = freshness_metrics()
    engine = get_slo_engine()
    prev_fm, prev_slo = fm.enabled, engine.enabled

    def set_enabled(on: bool) -> None:
        fm.configure(enabled=on)
        engine.configure(enabled=on)

    try:
        ab = _layer_ab("fresh", "freshness", set_enabled,
                       nodes, measure_pods, repeat)
    finally:
        fm.configure(enabled=prev_fm)
        engine.configure(enabled=prev_slo)
    return {
        "metric": f"freshness_overhead_pct[SchedulingBasic {nodes}nodes/"
                  f"{measure_pods}pods, SLI layer on/off A/B]",
        "value": round(ab["overhead_pct"], 2),
        "unit": "%",
        "freshness_on_pods_per_sec": round(ab["rates"]["on"], 1),
        "freshness_off_pods_per_sec": round(ab["rates"]["off"], 1),
        "noise_band_pct": round(ab["noise_pct"], 2),
        "within_noise": (abs(ab["overhead_pct"])
                         <= max(ab["noise_pct"], 1.0))
        if repeat > 1 else None,
    }


def run_mirror_ab(quick: bool = False) -> list:
    """Device-mirror on/off A/B riding the sustained harness
    (``--config mirrorab``): interleaved arms over the SAME seeded
    open-loop trace — the on arm is the committed mirror row (encode
    share near zero, per-cycle h2d strictly below the committed
    donation row), the off arm is the PR 12 delta-encode differential
    reference. The summary row adds a seeded in-process differential
    cell (node killed inside the scatter window; placements must be
    bit-identical across arms). Gated by perf_report's mirror_flags
    under ``--strict``."""
    import os

    from kubernetes_tpu.harness.chaos_mirror import run_chaos_mirror
    from kubernetes_tpu.harness.sustained import run_sustained_row

    # the committed PR 10 donated-buffer baseline this row must beat:
    # devscale_scaling.log donation_ab.on h2d_bytes_per_cycle
    h2d_budget = 618_497
    pods, qps, node_cpu, max_batch, timeout = (
        (2000, 1000.0, 16, 512, 300) if quick
        else (30_000, 5000.0, 32, 4096, 900))
    rows = []
    arms = {}
    prev = os.environ.get("KTPU_MIRROR")
    try:
        for arm in ("on", "off"):
            os.environ["KTPU_MIRROR"] = arm
            log(f"[mirror-ab] sustained arm mirror={arm}: {pods} pods "
                f"@ {qps:.0f}/s")
            row = run_sustained_row(pods=pods, qps=qps,
                                    node_cpu=node_cpu,
                                    max_batch=max_batch,
                                    wait_timeout=timeout,
                                    progress=log)
            row["metric"] = (f"mirror_sustained[arm={arm}, "
                             + row["metric"].split("[", 1)[1])
            row["mirror_arm"] = arm
            t = row.get("telemetry") or {}
            row["encode_share"] = t.get("encode_share")
            row["p99_budget_ms"] = 500
            cycles = int(t.get("cycles") or 0)
            if cycles:
                row["h2d_per_cycle_bytes"] = round(
                    float(t.get("h2d_bytes", 0)) / cycles)
            if arm == "on":
                row["encode_share_budget"] = 0.05
                row["h2d_per_cycle_budget_bytes"] = h2d_budget
                # exactly one re-seed is structural: the warmup
                # session rebuilds when the live trace starts; any
                # further reseed means journal gaps or inexpressible
                # deltas mid-run
                row["reseeds_allowed"] = 1
            arms[arm] = row
            rows.append(row)
    finally:
        if prev is None:
            os.environ.pop("KTPU_MIRROR", None)
        else:
            os.environ["KTPU_MIRROR"] = prev
    log("[mirror-ab] seeded differential cell (node_kill)")
    cell = run_chaos_mirror(14, scenario="node_kill", progress=log)
    on, off = arms["on"], arms["off"]
    overhead_pct = 0.0
    if off["value"] > 0:
        overhead_pct = 100.0 * (1.0 - on["value"] / off["value"])
    rows.append({
        "metric": (f"mirror_ab[sustained {pods}pods @ {qps:.0f}/s "
                   f"on/off + seeded node_kill differential]"),
        "value": round(overhead_pct, 2),
        "unit": "%",
        "mirror_on_pods_per_sec": on["value"],
        "mirror_off_pods_per_sec": off["value"],
        "h2d_per_cycle_on_bytes": on.get("h2d_per_cycle_bytes"),
        "h2d_per_cycle_off_bytes": off.get("h2d_per_cycle_bytes"),
        "encode_share_on": on.get("encode_share"),
        "encode_share_off": off.get("encode_share"),
        "differential_match": cell["differential_match"],
        "differential_lost_pods": cell["lost_pods"],
        "invariants_ok": bool(cell["ok"]
                              and on.get("invariants_ok")
                              and off.get("invariants_ok")),
    })
    return rows


def measure_serial(name: str, nodes: int, measure_pods: int,
                   serial_pods: int) -> float:
    serial_pods = min(serial_pods, measure_pods)
    ops = make_workload(name, nodes=nodes, init_pods=0,
                        measure_pods=serial_pods)
    t0 = time.time()
    serial = run_workload(f"{name}/serial", ops, use_batch=False,
                          wait_timeout=600, progress=log)
    log(f"serial baseline: {serial.pods_per_second:.1f} pods/s "
        f"({serial_pods} pods, wall {time.time() - t0:.1f}s)")
    return serial.pods_per_second


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=None,
                    choices=sorted(CONFIGS) + sorted(EXTRA_MATRIX)
                    + ["rest", "qos", "traceab", "profab", "freshab",
                       "autoscale", "scale10x", "devscale", "sustained",
                       "hotspot", "upgrade", "federation", "watchherd",
                       "mirrorab", "replay:storm", "replay:gangs",
                       "replay:tenancy"])
    ap.add_argument("--replay-seed", type=int, default=11,
                    help="trace seed for the replay:<family> rows "
                         "(same seed + trace → identical arrivals)")
    ap.add_argument("--rest-qps", type=float, default=5000.0)
    ap.add_argument("--all", action="store_true")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--skip-serial", action="store_true")
    # >=1k measured pods: a ~5s sample of a path with multi-second
    # warmup effects misstates the x-vs-serial denominator
    # (VERDICT r2 weak #7)
    ap.add_argument("--serial-pods", type=int, default=1000)
    ap.add_argument("--sharded-cpu", action="store_true")
    args = ap.parse_args()

    if args.sharded_cpu:
        # fresh interpreter: the virtual-device bootstrap must set
        # XLA_FLAGS before any JAX backend initializes — devscale owns
        # the ONE spawn-with-XLA_FLAGS entrypoint. The child imports
        # the package by module name, so it needs the repo root on its
        # path whatever cwd the parent was launched from.
        import os
        import subprocess

        env = dict(os.environ)
        root = os.path.dirname(os.path.abspath(__file__))
        env["PYTHONPATH"] = root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        cmd = [sys.executable, "-m", "kubernetes_tpu.harness.devscale",
               "--sharded-cpu"]
        if args.quick:
            cmd.append("--quick")
        raise SystemExit(subprocess.run(cmd, env=env).returncode)

    if args.config == "devscale":
        # the devices×throughput scaling row (sharded-by-default
        # solve): 1/2/4/8 virtual devices in spawned children, solve
        # throughput + donation on/off telemetry A/B per arm
        from kubernetes_tpu.harness.devscale import (
            QUICK_BATCH, QUICK_NODES, QUICK_PODS, run_devscale_row,
        )

        if args.quick:
            row = run_devscale_row(
                nodes=QUICK_NODES, pods=QUICK_PODS,
                max_batch=QUICK_BATCH, device_counts=(1, 2),
                donation_ab_devices=2, progress=log)
        else:
            row = run_devscale_row(progress=log)
        print(json.dumps(row), flush=True)
        return

    if args.config and args.config.startswith("replay:"):
        # the trace-replay rows (ISSUE 13): a scenario family driven
        # OPEN-LOOP through the REST fabric — pods arrive on a clock,
        # lifetimes expire into deletions, per-pod latency measured
        # from arrival, SLO verdicts + family invariants as the
        # verdict. --quick compresses the trace clock and scale.
        from kubernetes_tpu.workloads import run_replay_row

        family = args.config.split(":", 1)[1]
        if args.quick:
            row = run_replay_row(
                family, seed=args.replay_seed, scale=0.15,
                time_scale=0.3, rest=True, max_batch=256,
                qps=args.rest_qps if args.rest_qps > 0 else None,
                wait_timeout=300, progress=log)
        else:
            row = run_replay_row(
                family, seed=args.replay_seed, scale=1.0,
                time_scale=1.0, rest=True, max_batch=1024,
                qps=args.rest_qps if args.rest_qps > 0 else None,
                wait_timeout=900, progress=log)
        print(json.dumps(row), flush=True)
        return

    if args.config == "sustained":
        # the streaming-scheduler row (ISSUE 14): the headline-shaped
        # workload arriving OPEN-LOOP at 5k QPS through the replay
        # engine (not pre-created) — p99 arrival→bind is the headline,
        # the pipeline's overlap_share and the staleness SLO verdict
        # ride the row as its acceptance surface
        from kubernetes_tpu.harness.sustained import run_sustained_row

        if args.quick:
            row = run_sustained_row(pods=2000, qps=1000.0, node_cpu=16,
                                    max_batch=512, wait_timeout=300,
                                    progress=log)
        else:
            row = run_sustained_row(pods=30_000, qps=5000.0,
                                    node_cpu=32, max_batch=4096,
                                    wait_timeout=900, progress=log)
        print(json.dumps(row), flush=True)
        return

    if args.config == "hotspot":
        # the elastic-control-plane row (ISSUE 15): one namespace takes
        # 80% of the write load across three arms — balanced (honest
        # ceiling), hotspot (the failure mode, rebalancer off), and
        # rebalanced (the PartitionRebalancer splits the hot tenant
        # across the keyspace mid-run). The headline is the recovery
        # ratio (rebalanced steady-state rate / balanced rate, ≥0.8),
        # gated by zero lost pods / zero lost watch events / zero
        # relists of unmoved slices
        from kubernetes_tpu.harness.hotspot import run_hotspot_row

        if args.quick:
            row = run_hotspot_row(pods=6000, partitions=3,
                                  wait_timeout=300,
                                  rebalance_interval_s=0.12,
                                  cooldown_s=0.5, progress=log)
        else:
            row = run_hotspot_row(pods=24_000, partitions=3,
                                  wait_timeout=900, progress=log)
        print(json.dumps(row), flush=True)
        return

    if args.config == "upgrade":
        # the rolling-upgrade row (ISSUE 16): the WHOLE fleet — three
        # spawned partition servers + two scheduler replicas —
        # restarted exactly once each under sustained open-loop
        # arrivals; per partition: freeze → drain → verify → promote a
        # prespawned standby → reroute (abort-and-rollback on a blown
        # drain budget). Headline is p99 arrival→bind THROUGH the
        # roll; the verdict is the invariant set (zero lost pods, zero
        # lost/duplicated watch events, zero relists of unmoved
        # slices, exactly-once restarts, one epoch, mixed-version wire
        # guard clean), gated by perf_report's upgrade_flags
        from kubernetes_tpu.harness.upgrade import run_upgrade_row

        if args.quick:
            row = run_upgrade_row(pods=800, qps=100.0, partitions=2,
                                  replicas=1, node_cpu=16,
                                  wait_timeout=300, progress=log)
        else:
            row = run_upgrade_row(progress=log)
        print(json.dumps(row), flush=True)
        return

    if args.config == "federation":
        # the federated multi-cluster rows (ISSUE 18): three spawned
        # clusters (each its own apiserver + scheduler) behind the
        # federation tier, one open-loop storm each across two cells —
        # saturation spillover (cluster 0 pinned past capacity;
        # overflow must land remotely with the saturated cell's own
        # SLOs green) and cluster-loss (a whole cluster SIGKILLed
        # mid-storm; every orphan re-placed onto survivors within the
        # recovery budget). Verdict surface = zero lost pods
        # fleet-wide, gang atomicity across clusters, relists confined
        # to the dead cell, recovery ratio ≥ 0.8 — gated by
        # perf_report's federation_flags
        from kubernetes_tpu.harness.federation import run_federation_row

        for mode in ("spill", "loss"):
            if args.quick:
                row = run_federation_row(pods=400, qps=100.0,
                                         mode=mode, max_batch=128,
                                         wait_timeout=300,
                                         progress=log)
            else:
                row = run_federation_row(mode=mode, progress=log)
            print(json.dumps(row), flush=True)
        return

    if args.config == "watchherd":
        # the read-tier watch-herd rows (ISSUE 19): one arm per
        # replica count (0 / 1 / 4 spawned ReadReplica processes
        # tailing the owner's commit stream) with the SAME seeded
        # create/delete sequence — the replicas-off arm is the
        # differential control and every arm must land the identical
        # truth hash. 320 informers (≥10× any earlier row's stream
        # count) list+watch through the replicas while writes stay on
        # the owner; the scaling row judges fan-out per OWNER
        # cpu-second (the host time-shares every process, so
        # wall-clock aggregate measures the host, not the tier) and
        # the replica-kill cell closes the loop: zero lost events,
        # relists confined to the killed replica. Gated by
        # perf_report's readtier_flags
        from kubernetes_tpu.harness.watchherd import run_watchherd_row

        if args.quick:
            rows = run_watchherd_row(informers=64, creates=120,
                                     qps=20.0, herd_children=2,
                                     nodes=20, replica_arms=(0, 4),
                                     wait_timeout=300, progress=log)
        else:
            rows = run_watchherd_row(progress=log)
        for row in rows:
            row.pop("replica_stats", None)
            print(json.dumps(row), flush=True)
        return

    if args.config == "mirrorab":
        # the device-mirror rows (ISSUE 20): mirror on/off interleaved
        # over the same seeded sustained trace — the on arm commits
        # the tentpole's claim (encode share near zero, per-cycle h2d
        # strictly below the committed donation row), the off arm is
        # the delta-encode differential reference, and the summary row
        # carries the seeded in-process differential (bit-identical
        # placements through a node killed inside the scatter window).
        # Gated by perf_report's mirror_flags
        for row in run_mirror_ab(quick=args.quick):
            print(json.dumps(row), flush=True)
        return

    if args.config == "traceab":
        nodes, measure_pods = (200, 1000) if args.quick else (5000, 30000)
        print(json.dumps(run_trace_ab(
            nodes, measure_pods, repeat=1 if args.quick else 3)),
            flush=True)
        return

    if args.config == "profab":
        nodes, measure_pods = (200, 1000) if args.quick else (5000, 30000)
        print(json.dumps(run_profile_ab(
            nodes, measure_pods, repeat=1 if args.quick else 3)),
            flush=True)
        return

    if args.config == "freshab":
        nodes, measure_pods = (200, 1000) if args.quick else (5000, 30000)
        print(json.dumps(run_freshness_ab(
            nodes, measure_pods, repeat=1 if args.quick else 3)),
            flush=True)
        return

    if args.config == "autoscale":
        # the elastic row: start at 20% of needed capacity, burst to
        # 30k pods, let the autoscaler buy the rest — pods/s and
        # time-to-all-bound INCLUDE capacity acquisition
        from kubernetes_tpu.harness.elastic import run_autoscale_bench

        if args.quick:
            row = run_autoscale_bench(burst=1000, node_cpu=16,
                                      boot_latency=0.2, max_batch=1024,
                                      wait_timeout=300, progress=log)
        else:
            row = run_autoscale_bench(burst=30000, node_cpu=32,
                                      boot_latency=1.0, max_batch=4096,
                                      wait_timeout=1800, progress=log)
        print(json.dumps(row), flush=True)
        return

    if args.config == "scale10x":
        serial_rate = RECORDED_SERIAL_BASELINE["default"]
        print(json.dumps(run_scale10x_one(
            serial_rate, args.rest_qps, quick=args.quick)), flush=True)
        return

    if args.config == "rest":
        nodes, measure_pods = (200, 1000) if args.quick else (5000, 30000)
        serial_rate = RECORDED_SERIAL_BASELINE["default"]
        print(json.dumps(run_rest_one(
            nodes, measure_pods, serial_rate, args.rest_qps,
            repeat=1 if args.quick else 3)), flush=True)
        return

    if args.config == "qos":
        nodes, measure_pods = (200, 1000) if args.quick else (5000, 30000)
        serial_rate = RECORDED_SERIAL_BASELINE["default"]
        print(json.dumps(run_qos_one(
            nodes, measure_pods, serial_rate, args.rest_qps)),
            flush=True)
        return

    if args.config is not None:
        # single-workload mode: measures that workload's OWN serial rate
        name, nodes, init_pods, measure_pods = (
            CONFIGS.get(args.config) or EXTRA_MATRIX[args.config]
        )
        if args.quick:
            nodes, init_pods, measure_pods = 200, 0, 1000
        if args.skip_serial:
            serial_rate = RECORDED_SERIAL_BASELINE["default"]
            log(f"serial baseline (recorded): {serial_rate:.1f} pods/s")
        else:
            serial_rate = measure_serial(name, nodes, measure_pods,
                                         args.serial_pods)
        repeat = 3 if args.config == "headline" and not args.quick else 1
        print(json.dumps(run_one(args.config, name, nodes, init_pods,
                                 measure_pods, serial_rate, repeat=repeat)),
              flush=True)
        return

    # default (driver) + --all: ONE serial denominator for the whole
    # matrix — the headline SchedulingBasic 5k-node serial rate; each
    # non-headline row names that denominator explicitly
    serial_rate = RECORDED_SERIAL_BASELINE["default"]
    if not args.skip_serial:
        name, nodes, _, measure_pods = CONFIGS["headline"]
        if args.quick:
            nodes, measure_pods = 200, 1000
        serial_rate = measure_serial(name, nodes, measure_pods,
                                     args.serial_pods)
    matrix = {k: CONFIGS[k] for k in ("1", "2", "3", "4", "5")}
    if args.all:
        matrix.update(EXTRA_MATRIX)
    matrix["headline"] = CONFIGS["headline"]
    rest_row_cache = None
    for key in matrix_row_order(args.all):
        if key == "scale10x":
            # the 10×-tier partitioned-control-plane row (both A/B arms
            # + conflict cell) rides the default matrix right before
            # the QoS/REST/headline tail — its failure must not lose
            # the remaining rows
            try:
                scale_row = run_scale10x_one(serial_rate, args.rest_qps,
                                             quick=args.quick)
                scale_row["baseline"] = \
                    "SchedulingBasic 5k-node serial rate"
                print(json.dumps(scale_row), flush=True)
            except Exception as e:  # noqa: BLE001
                log(f"[scale10x] FAILED: {e}")
                print(json.dumps({
                    "metric": "pods_scheduled_per_sec"
                              "[Scale10x partitioned fabric]",
                    "value": 0.0, "unit": "pods/s", "vs_baseline": 0.0,
                    "error": str(e),
                }), flush=True)
            continue
        if key == "qos":
            # the noisy-tenant QoS row: the REST workload with 3
            # aggressor tenants hammering the fabric — APF's headline
            # claim (a hot tenant cannot starve the scheduler) as a
            # measured number, right before the REST row it's the
            # contended twin of. The REST row is computed HERE (and
            # cached for its own slot) so its median serves as the QoS
            # row's solo baseline — same configuration, no third
            # full-scale run.
            try:
                nodes, measure_pods = (200, 1000) if args.quick \
                    else (5000, 30000)
                rest_row_cache = run_rest_one(
                    nodes, measure_pods, serial_rate, args.rest_qps,
                    repeat=1 if args.quick else 3)
                rest_row_cache["baseline"] = \
                    "SchedulingBasic 5k-node serial rate"
                qos_row = run_qos_one(
                    nodes, measure_pods, serial_rate, args.rest_qps,
                    solo_baseline={
                        "pods_per_sec": rest_row_cache["value"],
                        "p99_latency_ms":
                            rest_row_cache["p99_latency_ms"],
                    })
                qos_row["baseline"] = \
                    "SchedulingBasic 5k-node serial rate"
                print(json.dumps(qos_row), flush=True)
            except Exception as e:  # noqa: BLE001 — must not lose the
                # remaining rows
                log(f"[qos] FAILED: {e}")
                print(json.dumps({
                    "metric": "noisy_tenant_qos"
                              "[SchedulingBasic REST fabric]",
                    "value": 0.0, "unit": "pods/s", "vs_baseline": 0.0,
                    "error": str(e),
                }), flush=True)
            continue
        if key == "rest":
            # the REST-fabric row rides the default matrix (VERDICT r4
            # #1: the headline must also survive the repo's own API
            # fabric) and prints IMMEDIATELY BEFORE the headline: the
            # driver tail-captures the end of stdout, and a row printed
            # mid-run falls out of the artifact (VERDICT r5 weak #1 —
            # tests/test_fastfabric.py guards this ordering). Usually
            # already measured by the QoS row above (its solo
            # baseline); recomputed only if that path failed.
            try:
                nodes, measure_pods = (200, 1000) if args.quick \
                    else (5000, 30000)
                rest_row = rest_row_cache if rest_row_cache is not None \
                    else run_rest_one(nodes, measure_pods, serial_rate,
                                      args.rest_qps,
                                      repeat=1 if args.quick else 3)
                rest_row["baseline"] = \
                    "SchedulingBasic 5k-node serial rate"
                print(json.dumps(rest_row), flush=True)
            except Exception as e:  # noqa: BLE001 — must not lose the
                # remaining rows
                log(f"[rest] FAILED: {e}")
                print(json.dumps({
                    "metric": "pods_scheduled_per_sec"
                              "[SchedulingBasic REST fabric]",
                    "value": 0.0, "unit": "pods/s", "vs_baseline": 0.0,
                    "error": str(e),
                }), flush=True)
            continue
        name, nodes, init_pods, measure_pods = matrix[key]
        if args.quick:
            nodes, init_pods, measure_pods = (
                200, min(init_pods, 200), 1000,
            )
        # configs 1-5 AND the headline are median-of-3 (tunnel variance
        # is ±30-40% across cold single runs — VERDICT r3 weak #3: one
        # cold run per family is noise, medians of back-to-back runs
        # hold; the extra wall time is minutes)
        repeat = 1 if args.quick or key in EXTRA_MATRIX else 3
        try:
            row = run_one(key, name, nodes, init_pods,
                          measure_pods, serial_rate, repeat=repeat)
        except Exception as e:  # noqa: BLE001 — one workload failing
            # must not lose the rest of the matrix (nor leave a
            # non-headline line last)
            log(f"[{key}] FAILED: {e}")
            row = {
                "metric": f"pods_scheduled_per_sec[{name} {nodes}nodes/"
                          f"{measure_pods}pods, TPU batch path]",
                "value": 0.0, "unit": "pods/s", "vs_baseline": 0.0,
                "error": str(e),
            }
        if key != "headline":
            row["baseline"] = "SchedulingBasic 5k-node serial rate"
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
