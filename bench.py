"""Benchmark entry point (driver-run on real TPU hardware).

Measures the headline metric from BASELINE.json — pods scheduled/sec at
5k nodes / 30k pending pods — on the TPU batch path, against the host
serial path measured on the same cluster (the stock-scheduler stand-in;
BASELINE.md: "absolute reference numbers must be measured, not cited").

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "pods/s", "vs_baseline": N}

Options (all optional):
    --config {1..5}   BASELINE.json config to run (default: headline 5k/30k)
    --quick           small scale smoke (CI-sized)
    --skip-serial     reuse the last recorded serial baseline
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from kubernetes_tpu.harness import make_workload, run_workload

# measured host-serial baselines (pods/s), updated by full runs
RECORDED_SERIAL_BASELINE = {
    "default": 40.0,   # 5k nodes, python serial path, measured 2026-07-30
}

CONFIGS = {
    # BASELINE.json configs -> (workload, nodes, init_pods, measure_pods)
    "1": ("SchedulingBasic", 100, 0, 1000),
    "2": ("SchedulingBasic", 1000, 0, 10000),
    "3": ("TopologySpreading", 5000, 0, 30000),
    "4": ("SchedulingPodAntiAffinity", 5000, 1000, 30000),
    "5": ("GangScheduling", 5000, 0, 30000),
    "headline": ("SchedulingBasic", 5000, 0, 30000),
}


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="headline", choices=sorted(CONFIGS))
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--skip-serial", action="store_true")
    ap.add_argument("--serial-pods", type=int, default=300)
    args = ap.parse_args()

    name, nodes, init_pods, measure_pods = CONFIGS[args.config]
    if args.quick:
        nodes, init_pods, measure_pods = 200, 0, 1000

    # --- serial baseline (host path = the stock-scheduler equivalent) ---
    if args.skip_serial:
        serial_rate = RECORDED_SERIAL_BASELINE["default"]
        log(f"serial baseline (recorded): {serial_rate:.1f} pods/s")
    else:
        serial_pods = min(args.serial_pods, measure_pods)
        ops = make_workload(name, nodes=nodes, init_pods=0,
                            measure_pods=serial_pods)
        t0 = time.time()
        serial = run_workload(f"{name}/serial", ops, use_batch=False,
                              wait_timeout=600, progress=log)
        serial_rate = serial.pods_per_second
        log(f"serial baseline: {serial_rate:.1f} pods/s "
            f"({serial_pods} pods, wall {time.time() - t0:.1f}s)")

    # --- TPU batch path --------------------------------------------------
    ops = make_workload(name, nodes=nodes, init_pods=init_pods,
                        measure_pods=measure_pods)
    t0 = time.time()
    # chunked batches: early chunks bind while later pods are still
    # queued, keeping p99 schedule-latency bounded at high throughput
    batch = run_workload(f"{name}/batch", ops, use_batch=True,
                         max_batch=min(measure_pods, 8192),
                         wait_timeout=1200, progress=log)
    log(f"batch: {batch.pods_per_second:.1f} pods/s "
        f"(wall {time.time() - t0:.1f}s, p99 latency "
        f"{batch.metrics.get('Perc99', 0):.0f}ms)")

    result = {
        "metric": f"pods_scheduled_per_sec[{name} {nodes}nodes/"
                  f"{measure_pods}pods, TPU batch path]",
        "value": round(batch.pods_per_second, 1),
        "unit": "pods/s",
        "vs_baseline": round(
            batch.pods_per_second / serial_rate, 2
        ) if serial_rate > 0 else 0.0,
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
