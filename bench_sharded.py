"""(thin shim) Multi-chip scaling-shape benchmark on the virtual CPU
device mesh — the implementation lives in
``kubernetes_tpu/harness/devscale.py`` since the devscale row landed,
so there is ONE spawn-with-XLA_FLAGS virtual-device bootstrap instead
of two diverging copies. Kept so the committed ``sharded_scaling.log``
workflow (``python bench_sharded.py [--quick]``) keeps working.

Must own the interpreter's JAX platform: forces an 8-device CPU host
before any backend initializes (``ensure_virtual_devices`` is the
shared mechanism; tests/conftest.py uses the same trick inline).
"""

from __future__ import annotations

import argparse

# jax-free import chain: devscale only touches jax inside its runner
# functions, so the bootstrap below still precedes backend init
from kubernetes_tpu.harness.devscale import (
    ensure_virtual_devices,
    run_sharded_cpu,
)

ensure_virtual_devices(8)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--breakdown-only", action="store_true")
    a = ap.parse_args()
    run_sharded_cpu(quick=a.quick, breakdown_only=a.breakdown_only)
