"""Multi-chip scaling-shape benchmark on the virtual CPU device mesh
(VERDICT r2 #4: "show the multi-chip scaling shape, not just
correctness").

Runs the headline workload (SchedulingBasic, 5k nodes / 30k pods by
default) END-TO-END through the full sidecar on:

- the single-device XLA planes scan (the same solver the sharded
  backend distributes), and
- the mesh-sharded planes backend over 2/4/8-device meshes
  (``parallel/sharded.py`` — node axis sharded over the mesh, XLA
  collectives over ICI on real hardware).

Absolute CPU wall-times say nothing about TPU rates; the SHAPE — device
solve-time vs mesh size at a fixed problem size — is the evidence that
the node-axis sharding pays (strong scaling) before multi-chip hardware
exists. Emits one JSON line per configuration:

    {"metric": "sharded_cpu[SchedulingBasic ...]", "devices": N,
     "device_solve_s": ..., "solve_speedup_vs_1dev": ...,
     "pods_per_second": ...}

Run via ``python bench.py --sharded-cpu`` or directly
(``python bench_sharded.py [--quick]``). Must own the interpreter's JAX
platform: forces an 8-device CPU host before any backend initializes
(the same mechanism as tests/conftest.py).
"""

from __future__ import annotations

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import argparse
import json
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _measure(name: str, nodes: int, pods: int, devices: int,
             init_pods: int = 0) -> dict:
    """One end-to-end run; returns the JSON row. devices=1 uses the
    single-device planes scan, >1 the mesh-sharded backend."""
    from kubernetes_tpu.harness import make_workload, run_workload

    if devices == 1:
        def backend_factory():
            from kubernetes_tpu.ops.pallas_solver import XlaPlanesBackend

            return XlaPlanesBackend()
    else:
        def backend_factory():
            from kubernetes_tpu.parallel import ShardedBackend, make_mesh

            return ShardedBackend(make_mesh(devices, batch_axis=1))

    seg = {}
    mem = {}

    def _shard_bytes(x) -> int:
        """Bytes ONE device holds for array x (sharded arrays report a
        single shard; replicated/host arrays their full size)."""
        try:
            return x.addressable_shards[0].data.nbytes
        except Exception:  # noqa: BLE001 — numpy / non-jax fields
            return int(getattr(x, "nbytes", 0))

    def hook(sched, bs):
        series = sched.metrics.batch_solve_duration._series
        for key, (_counts, total, count) in series.items():
            seg[key[0]] = (total, count)
        # per-device footprint of the resident mirror (static planes +
        # carried state): the multi-chip memory story — per-device bytes
        # shrink ~1/N with the node axis sharded, so clusters larger
        # than one chip's HBM fit the mesh
        import dataclasses

        total_b = 0
        for obj in (bs.session._static, bs.session._state):
            if obj is None:
                continue
            if dataclasses.is_dataclass(obj):
                for f in dataclasses.fields(obj):
                    v = getattr(obj, f.name)
                    if hasattr(v, "nbytes") or hasattr(
                            v, "addressable_shards"):
                        total_b += _shard_bytes(v)
            elif isinstance(obj, (tuple, list)):
                for v in obj:
                    total_b += _shard_bytes(v)
        mem["per_device_bytes"] = total_b

    ops = make_workload(name, nodes=nodes, init_pods=init_pods,
                        measure_pods=pods)
    t0 = time.time()
    # adaptive_chunk=False: every mesh size must solve the IDENTICAL
    # batch partition (the latency tuner would shrink slow
    # configurations' chunks and inflate their batch counts — round-3's
    # 13-vs-29 artifact measured the tuner, not the sharding)
    r = run_workload(
        f"{name}/sharded-{devices}dev", ops, use_batch=True,
        max_batch=4096, wait_timeout=3600, progress=log,
        backend_factory=backend_factory, result_hook=hook,
        adaptive_chunk=False,
    )
    dev_total, dev_batches = seg.get("device", (0.0, 0))
    return {
        "metric": f"sharded_cpu[{name} {nodes}nodes/{pods}pods]",
        "devices": devices,
        "pods_per_second": round(r.pods_per_second, 1),
        "device_solve_s": round(dev_total, 3),
        "solve_batches": dev_batches,
        "mirror_bytes_per_device": mem.get("per_device_bytes", 0),
        "wall_s": round(time.time() - t0, 1),
    }


def _breakdown(n_nodes: int, batch_pods: int, device_counts) -> list:
    """Per-batch compute-vs-collective split on one representative
    solve batch. The ablated build (``collectives=False``) replaces
    every cross-shard op with a local stand-in of identical arithmetic
    shape, so full-minus-ablated wall time isolates pure collective
    cost — the quantity shared-silicon virtual devices inflate (every
    shard's collective work serializes onto the same cores) and real
    ICI does not."""
    import jax

    from kubernetes_tpu.ops import BatchEncoder
    from kubernetes_tpu.ops.pallas_solver import XlaPlanesBackend
    from kubernetes_tpu.ops.solver import SolverParams, pack_podin
    from kubernetes_tpu.parallel.sharded import (
        _build_solve,
        _prepare_sharded,
        make_mesh,
    )
    from kubernetes_tpu.scheduler.snapshot import new_snapshot
    from kubernetes_tpu.testing import MakeNode, MakePod

    nodes = [
        MakeNode().name(f"n{i}")
        .capacity({"cpu": "32", "memory": "64Gi", "pods": "110"}).obj()
        for i in range(n_nodes)
    ]
    pods = [
        MakePod().name(f"p{i}").uid(f"u{i}")
        .req({"cpu": "100m", "memory": "200Mi"}).obj()
        for i in range(batch_pods)
    ]
    snap = new_snapshot([], nodes)
    cluster, batch = BatchEncoder(snap, pad_nodes=128).encode(
        pods, pad_pods=batch_pods
    )
    params = SolverParams()
    ints, floats = pack_podin(batch)

    def timed(fn, reps: int = 3) -> float:
        fn()  # warm (compile)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            best = min(best, time.perf_counter() - t0)
        return best

    rows = []
    # single-device reference: the same planes scan the sharded build
    # distributes
    be = XlaPlanesBackend()
    static1, state1 = be.prepare(cluster, batch)
    base_s = timed(
        lambda: be.solve(params, static1, state1, ints, floats)[0]
    )
    rows.append({
        "metric": f"sharded_breakdown[{n_nodes}nodes/{batch_pods}pod-batch]",
        "devices": 1, "batch_solve_s": round(base_s, 3),
        "compute_s": round(base_s, 3), "collective_s": 0.0,
        "collective_frac": 0.0,
    })
    # 1-shard control: the SAME shard_map build on a 1-device mesh —
    # collectives are no-ops, so (control - planes-scan baseline)
    # isolates the shard_map machinery's constant overhead from
    # anything that scales with shard count
    for d in [1] + list(device_counts):
        mesh = make_mesh(d, batch_axis=1)
        sstatic, sstate = _prepare_sharded(cluster, batch, mesh)
        args = (sstatic.sc_meta, sstatic.ints, sstatic.f32s,
                sstate.planes, sstate.totals, ints, floats, ints,
                sstatic.has_dom)
        times = {}
        for collectives in (True, False):
            run = _build_solve(
                mesh, params, sstatic.r, sstatic.sc, sstatic.t,
                sstatic.u, sstatic.v, with_counts=False,
                any_hard=sstatic.any_hard, collectives=collectives,
            )
            with mesh:
                times[collectives] = timed(lambda: run(*args)[0])
        coll = max(times[True] - times[False], 0.0)
        rows.append({
            "metric":
                f"sharded_breakdown[{n_nodes}nodes/{batch_pods}pod-batch]"
                + ("(1-shard shard_map control)" if d == 1 else ""),
            "devices": d,
            "batch_solve_s": round(times[True], 3),
            "compute_s": round(times[False], 3),
            "collective_s": round(coll, 3),
            "collective_frac": round(coll / max(times[True], 1e-9), 3),
        })
    return rows


def main(quick: bool = False, breakdown_only: bool = False) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    n_dev = len(jax.devices())
    if n_dev < 8:
        log(f"WARNING: only {n_dev} CPU devices (wanted 8); "
            "XLA_FLAGS was set too late for this interpreter — run "
            "bench_sharded.py directly")
    name = "SchedulingBasic"
    nodes, pods = (512, 4096) if quick else (5000, 30000)
    rows = []
    for devices in (1, 2, 4, 8):
        if devices > n_dev or breakdown_only:
            continue
        log(f"--- {devices} device(s) ---")
        rows.append(_measure(name, nodes, pods, devices))
    # preemption-heavy scaling row (VERDICT r4 next #4): the mass-
    # decline -> vectorized screen -> victim-planner flow on the mesh
    # path; fillers exactly fill the cluster so every measured pod
    # preempts
    p_nodes, p_pods = (256, 256) if quick else (1000, 1000)
    for devices in (1, 8):
        if devices > n_dev or breakdown_only:
            continue
        log(f"--- Preemption, {devices} device(s) ---")
        row = _measure("Preemption", p_nodes, p_pods, devices,
                       init_pods=p_nodes)
        print(json.dumps(row), flush=True)
    base = next((r for r in rows if r["devices"] == 1), None)
    for r in rows:
        if base and r["device_solve_s"] > 0:
            r["solve_speedup_vs_1dev"] = round(
                base["device_solve_s"] / r["device_solve_s"], 2
            )
        print(json.dumps(r), flush=True)
    log("--- per-batch compute/collective breakdown ---")
    bd_nodes, bd_pods = (512, 1024) if quick else (5000, 4096)
    for row in _breakdown(bd_nodes, bd_pods,
                          [d for d in (2, 4, 8) if d <= n_dev]):
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--breakdown-only", action="store_true")
    a = ap.parse_args()
    main(quick=a.quick, breakdown_only=a.breakdown_only)
