"""Multi-chip scaling-shape benchmark on the virtual CPU device mesh
(VERDICT r2 #4: "show the multi-chip scaling shape, not just
correctness").

Runs the headline workload (SchedulingBasic, 5k nodes / 30k pods by
default) END-TO-END through the full sidecar on:

- the single-device XLA planes scan (the same solver the sharded
  backend distributes), and
- the mesh-sharded planes backend over 2/4/8-device meshes
  (``parallel/sharded.py`` — node axis sharded over the mesh, XLA
  collectives over ICI on real hardware).

Absolute CPU wall-times say nothing about TPU rates; the SHAPE — device
solve-time vs mesh size at a fixed problem size — is the evidence that
the node-axis sharding pays (strong scaling) before multi-chip hardware
exists. Emits one JSON line per configuration:

    {"metric": "sharded_cpu[SchedulingBasic ...]", "devices": N,
     "device_solve_s": ..., "solve_speedup_vs_1dev": ...,
     "pods_per_second": ...}

Run via ``python bench.py --sharded-cpu`` or directly
(``python bench_sharded.py [--quick]``). Must own the interpreter's JAX
platform: forces an 8-device CPU host before any backend initializes
(the same mechanism as tests/conftest.py).
"""

from __future__ import annotations

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import argparse
import json
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _measure(name: str, nodes: int, pods: int, devices: int) -> dict:
    """One end-to-end run; returns the JSON row. devices=1 uses the
    single-device planes scan, >1 the mesh-sharded backend."""
    from kubernetes_tpu.harness import make_workload, run_workload

    if devices == 1:
        def backend_factory():
            from kubernetes_tpu.ops.pallas_solver import XlaPlanesBackend

            return XlaPlanesBackend()
    else:
        def backend_factory():
            from kubernetes_tpu.parallel import ShardedBackend, make_mesh

            return ShardedBackend(make_mesh(devices, batch_axis=1))

    seg = {}
    mem = {}

    def _shard_bytes(x) -> int:
        """Bytes ONE device holds for array x (sharded arrays report a
        single shard; replicated/host arrays their full size)."""
        try:
            return x.addressable_shards[0].data.nbytes
        except Exception:  # noqa: BLE001 — numpy / non-jax fields
            return int(getattr(x, "nbytes", 0))

    def hook(sched, bs):
        series = sched.metrics.batch_solve_duration._series
        for key, (_counts, total, count) in series.items():
            seg[key[0]] = (total, count)
        # per-device footprint of the resident mirror (static planes +
        # carried state): the multi-chip memory story — per-device bytes
        # shrink ~1/N with the node axis sharded, so clusters larger
        # than one chip's HBM fit the mesh
        import dataclasses

        total_b = 0
        for obj in (bs.session._static, bs.session._state):
            if obj is None:
                continue
            if dataclasses.is_dataclass(obj):
                for f in dataclasses.fields(obj):
                    v = getattr(obj, f.name)
                    if hasattr(v, "nbytes") or hasattr(
                            v, "addressable_shards"):
                        total_b += _shard_bytes(v)
            elif isinstance(obj, (tuple, list)):
                for v in obj:
                    total_b += _shard_bytes(v)
        mem["per_device_bytes"] = total_b

    ops = make_workload(name, nodes=nodes, init_pods=0, measure_pods=pods)
    t0 = time.time()
    r = run_workload(
        f"{name}/sharded-{devices}dev", ops, use_batch=True,
        max_batch=4096, wait_timeout=3600, progress=log,
        backend_factory=backend_factory, result_hook=hook,
    )
    dev_total, dev_batches = seg.get("device", (0.0, 0))
    return {
        "metric": f"sharded_cpu[{name} {nodes}nodes/{pods}pods]",
        "devices": devices,
        "pods_per_second": round(r.pods_per_second, 1),
        "device_solve_s": round(dev_total, 3),
        "solve_batches": dev_batches,
        "mirror_bytes_per_device": mem.get("per_device_bytes", 0),
        "wall_s": round(time.time() - t0, 1),
    }


def main(quick: bool = False) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    n_dev = len(jax.devices())
    if n_dev < 8:
        log(f"WARNING: only {n_dev} CPU devices (wanted 8); "
            "XLA_FLAGS was set too late for this interpreter — run "
            "bench_sharded.py directly")
    name = "SchedulingBasic"
    nodes, pods = (512, 4096) if quick else (5000, 30000)
    rows = []
    for devices in (1, 2, 4, 8):
        if devices > n_dev:
            continue
        log(f"--- {devices} device(s) ---")
        rows.append(_measure(name, nodes, pods, devices))
    base = next((r for r in rows if r["devices"] == 1), None)
    for r in rows:
        if base and r["device_solve_s"] > 0:
            r["solve_speedup_vs_1dev"] = round(
                base["device_solve_s"] / r["device_solve_s"], 2
            )
        print(json.dumps(r), flush=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(quick=ap.parse_args().quick)
